"""Compiled train/eval steps — the hot loop.

Replaces the reference's per-batch torch path (SURVEY §3.4: DDP forward ->
cross_entropy -> scaler backward -> Reducer allreduce every micro-step ->
optimizer/scheduler step) with one jitted function per effective step:

- forward+backward via `jax.value_and_grad`, bf16 compute / fp32 params;
- the gradient all-reduce is *implied* by differentiating a loss computed
  over the globally-sharded batch — XLA inserts the psum and overlaps it
  (no DDP Reducer, SURVEY §2.3-N6);
- gradient accumulation is an in-graph `lax.scan` over micro-batches that
  syncs ONCE per effective step — a deliberate fix of the reference's
  allreduce-every-micro-step behavior (run.py:257, SURVEY §2.1);
- eval metrics are accumulated in-graph as masked (loss_sum, correct, count)
  sums, fixing the reference's padded-duplicate eval bias (run.py:298 plain
  `gather` vs `gather_for_metrics`, SURVEY §2.1).

Batch convention: dict with "video" (single-pathway) or "slow"/"fast"
(SlowFast packing), "label" int32, optional "mask" float32 (1.0 = real
sample, 0.0 = padding). With gradient accumulation G>1, every leaf carries a
leading (G, B, ...) micro-step axis laid out by the data pipeline, so no
device resharding is needed to slice micro-batches.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorchvideo_accelerate_tpu.parallel.mesh import batch_axes
from pytorchvideo_accelerate_tpu.trainer.train_state import TrainState


def model_inputs(batch: dict):
    """Map a batch dict to the model's input convention."""
    if "slow" in batch:
        return (batch["slow"], batch["fast"])
    return batch["video"]


def device_normalize_batch(batch: dict, norm) -> dict:
    """In-graph normalize for u8-through clips (data/transforms.py
    `output_dtype="uint8"`): the host ships raw uint8 — 4x less
    host->HBM transfer than fp32 — and the graph applies the same
    `x/255` + mean/std affine the host path fuses (`normalize_u8`).
    Computed in f32 so the model's own compute-dtype cast produces
    bit-identical bf16 to the host-normalized path; XLA fuses the
    affine into the first conv's input read, so nothing extra is
    materialized in HBM. No-op when `norm` is None or a clip is
    already floating-point."""
    if norm is None:
        return batch
    mean, std = norm
    mean32 = jnp.asarray(mean, jnp.float32)
    std32 = jnp.asarray(std, jnp.float32)
    scale = 1.0 / (255.0 * std32)
    bias = -mean32 / std32

    def f(x):
        if x.dtype != jnp.uint8:
            return x
        return x.astype(jnp.float32) * scale + bias

    out = dict(batch)
    for k in ("video", "slow", "fast"):
        if k in out:
            out[k] = f(out[k])
    return out


def _constrain_batch(batch: dict, mesh, leading_micro: bool) -> dict:
    """Pin the (global) batch dim to the mesh's DP axes inside the graph
    (("data","fsdp") on the library mesh, ("data",) on the 2-D train mesh)."""
    daxes = batch_axes(mesh)
    axes = (None, daxes) if leading_micro else (daxes,)

    def cons(x):
        spec = P(*axes, *([None] * (x.ndim - len(axes))))
        return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    return jax.tree.map(cons, batch)


def assert_batch_contract(batch: dict, leading_micro: bool = False) -> None:
    """Trace-time batch-contract checks (SURVEY §5 sanitizers): ranks,
    dtypes, and matching leading dims. On when TrainConfig.debug_asserts is
    set — pure trace-time, so zero runtime cost in the compiled step."""
    import chex

    lead = 2 if leading_micro else 1
    clips = [batch[k] for k in ("slow", "fast", "video") if k in batch]
    assert clips, "batch has neither 'video' nor 'slow'/'fast' clips"
    for c in clips:
        # (B, T, H, W, C) + optional micro axis + optional view axis
        chex.assert_rank(c, {4 + lead, 5 + lead})
    if "label" in batch:
        chex.assert_rank(batch["label"], lead)
        chex.assert_type(batch["label"], jnp.int32)
        chex.assert_equal_shape_prefix([clips[0], batch["label"]], lead)
    if batch.get("mask") is not None:
        chex.assert_type(batch["mask"], jnp.float32)
        chex.assert_equal_shape_prefix([clips[0], batch["mask"]], lead)


def _loss_and_metrics(logits, labels, mask, label_smoothing: float):
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
    if label_smoothing > 0:
        onehot = optax.smooth_labels(onehot, label_smoothing)
    losses = optax.softmax_cross_entropy(logits, onehot)
    count = mask.sum()
    loss = (losses * mask).sum() / jnp.maximum(count, 1.0)
    correct = ((jnp.argmax(logits, -1) == labels) * mask).sum()
    return loss, correct, count


def _topk_correct(logits, labels, mask, k: int = 5):
    """Masked top-k hit count (Kinetics convention reports top-1 AND top-5;
    the reference's torchmetrics Accuracy is top-1 only)."""
    k = min(k, logits.shape[-1])
    _, top = lax.top_k(logits.astype(jnp.float32), k)
    hit = (top == labels[..., None]).any(-1)
    return (hit * mask).sum()


def _fold_micro_axis(batch: dict) -> dict:
    """Fold the leading (G, B, ...) accumulation micro axis into the batch
    dim — (G*B, ...). The pipelined step (parallel/pipeline.py) consumes
    the WHOLE effective batch in one forward and re-slices it into the
    plan's microbatches inside the stage schedule, so the outer
    accumulation scan (which would serialize a full pipeline fill+drain
    per micro-step) disappears; the loss over the folded batch equals the
    mean of per-micro losses, and its gradient equals the accumulated
    gradient over G micro-steps divided by G — the same update (bitwise
    on the rng-free supervised path; an rng objective like the VideoMAE
    tube mask draws ONE stream per effective batch here instead of one
    per micro-step — both valid samplings, not a numerics drift)."""
    return jax.tree.map(
        lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]), batch)


def _make_update_step(
    grad_fn: Callable,
    tx: optax.GradientTransformation,
    mesh,
    accum_steps: int,
    lr_schedule: Optional[Callable],
    with_accuracy: bool,
    debug_asserts: bool = False,
    ema_decay: float = 0.0,
    health_metrics: bool = False,
    guard_skip: bool = False,
    pipeline=None,
) -> Callable:
    """Shared machinery of the supervised and self-supervised steps.

    `grad_fn(params, batch_stats, batch, key) -> ((loss, (new_stats, correct,
    count)), grads)` — a value_and_grad with has_aux; the self-supervised
    wrapper passes batch_stats/correct/count through untouched. Gradient
    accumulation is an in-graph `lax.scan` over the leading micro-batch axis
    syncing ONCE per effective step; the returned step is jitted with state
    donation (params update in place in HBM).

    `guard_skip` (reliability/guard.py TrainGuard): a step whose loss or
    grad norm is nonfinite discards its own update IN-GRAPH — every state
    leaf keeps its old value via `jnp.where`, only the step counter
    advances — so a single NaN batch can never poison params/EMA/optimizer
    state while the (one-step-delayed, pipelining-preserving) host
    detector decides whether to escalate. A data-dependent select on a
    static predicate shape: no recompile, one extra `metrics["skipped"]`
    flag. Off (the default): the branch is not traced at all —
    structurally zero overhead.

    `pipeline` (parallel/pipeline.PipelinePlan, active): the model's trunk
    runs as a P-stage SPMD pipeline, and the microbatch STREAM through the
    stages replaces the outer accumulation scan — the (G, B, ...) micro
    axis is folded into one (G*B, ...) forward whose in-graph schedule
    keeps every stage busy (`_fold_micro_axis`; the outer scan would
    serialize a pipeline fill+drain per micro-step, P-1 extra bubbles).
    Plain autodiff through the stage scan, no custom VJP; state donation
    is unchanged (graphcheck's donation pass covers the pipelined step as
    its own target)."""
    pipelined = pipeline is not None and getattr(pipeline, "active", False)

    def step(state: TrainState, batch: dict, key) -> tuple:
        if debug_asserts:
            assert_batch_contract(batch, leading_micro=accum_steps > 1)
        if accum_steps > 1 and pipelined:
            batch = _constrain_batch(batch, mesh, leading_micro=True)
            batch = _fold_micro_axis(batch)
        if accum_steps == 1 or pipelined:
            batch = _constrain_batch(batch, mesh, leading_micro=False)
            (loss, (new_stats, correct, count)), grads = grad_fn(
                state.params, state.batch_stats, batch, key
            )
        else:
            batch = _constrain_batch(batch, mesh, leading_micro=True)

            def micro(carry, mb):
                grads_acc, stats, i = carry
                (loss_i, (stats, corr_i, cnt_i)), g = grad_fn(
                    state.params, stats, mb, jax.random.fold_in(key, i)
                )
                grads_acc = jax.tree.map(jnp.add, grads_acc, g)
                return (grads_acc, stats, i + 1), (loss_i, corr_i, cnt_i)

            zeros = jax.tree.map(jnp.zeros_like, state.params)
            (grads, new_stats, _), (losses, corrs, cnts) = lax.scan(
                micro, (zeros, state.batch_stats, 0), batch
            )
            grads = jax.tree.map(lambda g: g / accum_steps, grads)
            loss = jnp.mean(losses)
            correct, count = corrs.sum(), cnts.sum()

        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_ema = state.ema_params
        if ema_decay > 0 and state.ema_params is not None:
            # in-graph EMA: pure VPU elementwise, fused with the update
            new_ema = jax.tree.map(
                lambda e, p: e * ema_decay + p.astype(e.dtype)
                * (1.0 - ema_decay),
                state.ema_params, new_params)
        grad_norm = optax.global_norm(grads)
        skipped = None
        if guard_skip:
            # in-graph skip-batch (TrainGuard): a nonfinite loss or grad
            # norm means this update is poison — keep every old leaf
            # (params, BN stats, optimizer state, EMA), advance only the
            # step counter so host/step bookkeeping stays aligned
            ok = jnp.isfinite(loss) & jnp.isfinite(grad_norm)

            def _keep(new, old):
                return jnp.where(ok, new, old)

            new_params = jax.tree.map(_keep, new_params, state.params)
            new_stats = jax.tree.map(_keep, new_stats, state.batch_stats)
            new_opt_state = jax.tree.map(_keep, new_opt_state,
                                         state.opt_state)
            if new_ema is not None:
                new_ema = jax.tree.map(_keep, new_ema, state.ema_params)
            skipped = 1.0 - ok.astype(jnp.float32)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
            ema_params=new_ema,
        )
        metrics = {"loss": loss, "grad_norm": grad_norm}
        if skipped is not None:
            metrics["skipped"] = skipped
        if health_metrics:
            # training-health gauges computed IN-GRAPH (obs/: a few extra
            # reductions XLA fuses into the update — cheap on device, and
            # they ride the same async metrics fetch as loss/grad_norm):
            # global param norm, update/param ratio (the "is the LR sane"
            # signal — healthy runs sit around 1e-3, a spike means the
            # update is rewriting the weights), and a non-finite-loss flag
            # the host accumulates into a counter.
            param_norm = optax.global_norm(new_params)
            metrics["param_norm"] = param_norm
            metrics["update_ratio"] = (
                optax.global_norm(updates) / jnp.maximum(param_norm, 1e-12))
            metrics["nonfinite"] = 1.0 - jnp.isfinite(loss).astype(
                jnp.float32)
        if with_accuracy:
            metrics["accuracy"] = correct / jnp.maximum(count, 1.0)
        if lr_schedule is not None:
            metrics["lr"] = lr_schedule(state.step)
        return new_state, metrics

    # state donation, VERIFIED: the graphcheck donation pass
    # (analysis/gc_donation.py) walks the compiled input_output_alias map
    # and proves every state leaf aliases — disarmed AND guard-armed (the
    # jnp.where skip branch above must not break aliasing) — with zero
    # donatable leaves left undeclared; bench --smoke gates on it. An
    # aval drift here (a leaf that changes dtype/shape across the step)
    # would silently double-buffer that leaf — the pass reports the bytes.
    return jax.jit(step, donate_argnums=0)


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh,
    accum_steps: int = 1,
    label_smoothing: float = 0.0,
    lr_schedule: Optional[Callable] = None,
    debug_asserts: bool = False,
    device_normalize=None,
    mixup_alpha: float = 0.0,
    cutmix_alpha: float = 0.0,
    ema_decay: float = 0.0,
    health_metrics: bool = False,
    guard_skip: bool = False,
    pipeline=None,
) -> Callable:
    """Build the supervised `step(state, batch, dropout_key) ->
    (state, metrics)` (see `_make_update_step`). `device_normalize`:
    (mean, std) for u8-through batches (`device_normalize_batch`).
    `mixup_alpha > 0` / `cutmix_alpha > 0`: in-graph mixup / cutmix (the
    MViT/SlowFast K400 recipes' augmentations, free of host cost), both
    expressed as one per-pixel weight w against the FLIPPED batch:
    out = w*x + (1-w)*x_flip — mixup is w = lam everywhere, cutmix is a
    spatial box of zeros (shared across time, the video convention) —
    with loss lam_eff*CE(y) + (1-lam_eff)*CE(y_flip), lam_eff = mean(w).
    Both on: a coin picks one per forward — i.e. per MICRO-batch under
    gradient accumulation, each drawing its own mode/lambda/box (timm's
    switching, at micro granularity). Reported accuracy counts the
    dominant label."""

    def forward_loss(params, batch_stats, batch, key):
        batch = device_normalize_batch(batch, device_normalize)
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(batch["label"].shape, jnp.float32)
        labels2 = None
        lam = 1.0
        if mixup_alpha > 0 or cutmix_alpha > 0:
            if batch.get("mask") is not None:
                raise ValueError(
                    "mixup/cutmix with an explicit batch mask is "
                    "unsupported: padded rows would mix into real clips "
                    "(the train loader is drop_last, so this can't arise "
                    "through Trainer)")
            # mixing runs AFTER the u8 normalize (floats required).
            # Pairing is the flipped batch (timm's convention): a STATIC
            # reversal, which GSPMD lowers to a one-hop collective permute
            # of the clip tensor — a random global permutation would force
            # a cross-device gather of the whole batch every step. Every
            # clip pathway flips together so slow/fast stay paired.
            key, kmix, kbox, kswitch = jax.random.split(key, 4)
            some_clip = next(batch[k] for k in ("video", "slow", "fast")
                             if k in batch)
            hh, ww = some_clip.shape[-3], some_clip.shape[-2]
            use_cutmix = cutmix_alpha > 0 and (
                mixup_alpha <= 0
                or jax.random.bernoulli(kswitch))
            if mixup_alpha > 0 and cutmix_alpha > 0:
                lam_mix = jax.random.beta(kmix, mixup_alpha, mixup_alpha)
                lam_cut = jax.random.beta(kmix, cutmix_alpha, cutmix_alpha)
            else:
                a = mixup_alpha if mixup_alpha > 0 else cutmix_alpha
                lam_mix = lam_cut = jax.random.beta(kmix, a, a)

            def _cut_weight():
                # spatial box of the flipped clip, shared across time
                # (video cutmix convention); area approx (1 - lam_cut)
                rh = jnp.sqrt(1.0 - lam_cut) * hh
                rw = jnp.sqrt(1.0 - lam_cut) * ww
                cy = jax.random.uniform(kbox, (), minval=0.0, maxval=1.0) * hh
                cx = jax.random.uniform(
                    jax.random.fold_in(kbox, 1), (), minval=0.0,
                    maxval=1.0) * ww
                y0, y1 = cy - rh / 2, cy + rh / 2
                x0, x1 = cx - rw / 2, cx + rw / 2
                ih = jax.lax.broadcasted_iota(jnp.float32, (hh, ww), 0)
                iw = jax.lax.broadcasted_iota(jnp.float32, (hh, ww), 1)
                inside = ((ih >= y0) & (ih < y1) & (iw >= x0) & (iw < x1))
                return 1.0 - inside.astype(jnp.float32)  # (H, W)

            if cutmix_alpha > 0:
                w_hw = jnp.where(use_cutmix, _cut_weight(),
                                 jnp.full((hh, ww), lam_mix))
            else:
                w_hw = jnp.full((hh, ww), lam_mix)
            # effective label weight = mean pixel weight (exact for both)
            lam = jnp.mean(w_hw)
            w = w_hw[None, None, :, :, None]  # (1,1,H,W,1) vs (B,T,H,W,C)
            batch = dict(batch)
            for k in ("video", "slow", "fast"):
                if k in batch:
                    x = batch[k]
                    mixed = (w * x.astype(jnp.float32)
                             + (1.0 - w) * x[::-1].astype(jnp.float32))
                    batch[k] = mixed.astype(x.dtype)
            labels2 = batch["label"][::-1]
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats},
            model_inputs(batch),
            train=True,
            rngs={"dropout": key},
            mutable=["batch_stats"],
        )
        if labels2 is not None:
            loss_a, correct_a, count = _loss_and_metrics(
                logits, batch["label"], mask, label_smoothing)
            loss_b, correct_b, _ = _loss_and_metrics(
                logits, labels2, mask[::-1], label_smoothing)
            loss = lam * loss_a + (1.0 - lam) * loss_b
            # dominant-label accuracy (the standard mixup report)
            correct = jnp.where(lam >= 0.5, correct_a, correct_b)
        else:
            loss, correct, count = _loss_and_metrics(
                logits, batch["label"], mask, label_smoothing
            )
        return loss, (updates["batch_stats"], correct, count)

    grad_fn = jax.value_and_grad(forward_loss, has_aux=True)
    return _make_update_step(grad_fn, tx, mesh, accum_steps, lr_schedule,
                             with_accuracy=True, debug_asserts=debug_asserts,
                             ema_decay=ema_decay,
                             health_metrics=health_metrics,
                             guard_skip=guard_skip, pipeline=pipeline)


def make_pretrain_step(
    model,
    tx: optax.GradientTransformation,
    mesh,
    accum_steps: int = 1,
    lr_schedule: Optional[Callable] = None,
    debug_asserts: bool = False,
    ema_decay: float = 0.0,
    health_metrics: bool = False,
    guard_skip: bool = False,
    pipeline=None,
) -> Callable:
    """Build the VideoMAE self-supervised step: `step(state, batch, key) ->
    (state, metrics)`. No labels; batch_stats pass through unchanged (pure-LN
    ViT keeps `{}`); the model returns its own reconstruction loss. The rng
    key feeds both the tube mask and dropout streams. `pipeline`: an
    active plan folds the accumulation micro axis into the stage
    schedule's microbatch stream (see `_make_update_step`)."""

    def forward_loss(params, batch_stats, batch, key):
        kmask, kdrop = jax.random.split(key)
        out = model.apply(
            {"params": params}, batch["video"], train=True,
            rngs={"mask": kmask, "dropout": kdrop},
        )
        zero = jnp.zeros((), jnp.float32)
        return out["loss"], (batch_stats, zero, zero)

    grad_fn = jax.value_and_grad(forward_loss, has_aux=True)
    return _make_update_step(grad_fn, tx, mesh, accum_steps, lr_schedule,
                             with_accuracy=False, debug_asserts=debug_asserts,
                             ema_decay=ema_decay,
                             health_metrics=health_metrics,
                             guard_skip=guard_skip, pipeline=pipeline)


def make_pretrain_eval_step(model, mesh) -> Callable:
    """Eval for MAE pretraining: reconstruction loss on held-out clips with
    a deterministic mask (same SumMetrics contract; accuracy reads 0)."""

    def eval_step(state: TrainState, batch: dict) -> dict:
        batch = _constrain_batch(batch, mesh, leading_micro=False)
        eval_params = (state.ema_params if state.ema_params is not None
                       else state.params)
        out = model.apply(
            {"params": eval_params}, batch["video"], train=False,
            rngs={"mask": jax.random.key(0)},
        )
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones((batch["video"].shape[0],), jnp.float32)
        # per-sample recon loss from pred/target so zero-padded val-tail
        # clips don't bias the mean (parity with the supervised eval fix)
        per_sample = jnp.mean(
            (out["pred"].astype(jnp.float32)
             - out["target"].astype(jnp.float32)) ** 2,
            axis=tuple(range(1, out["pred"].ndim)),
        )
        count = mask.sum()
        return {"loss_sum": (per_sample * mask).sum(),
                "correct": jnp.zeros((), jnp.float32), "count": count}

    return jax.jit(eval_step)


def fold_views(inputs):
    """Fold the per-video view axis into the batch dim: clip leaves shaped
    (B, V, T, H, W, C) become (B*V, T, H, W, C); single-view (rank-5) inputs
    pass through. Returns `(inputs, num_views)`. Works on the single-pathway
    tensor and the SlowFast (slow, fast) tuple alike."""
    first = inputs[0] if isinstance(inputs, tuple) else inputs
    num_views = first.shape[1] if first.ndim == 6 else 1
    if num_views > 1:
        inputs = jax.tree.map(
            lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]),
            inputs,
        )
    return inputs, num_views


def multiview_logits(forward: Callable, inputs):
    """The multi-view logit-averaging protocol (reference uniform-sampler
    tiling, run.py:163), shared by `evaluate()` and the serving engine so
    their top-1 agrees by construction: fold views into the batch (one big
    MXU-friendly forward), then view-average the logits in fp32 before any
    argmax. `forward(clips) -> logits` over view-folded clips."""
    inputs, num_views = fold_views(inputs)
    logits = forward(inputs)
    if num_views > 1:
        logits = logits.astype(jnp.float32).reshape(
            -1, num_views, logits.shape[-1]
        ).mean(axis=1)
    return logits


def make_eval_step(model, mesh, label_smoothing: float = 0.0,
                   device_normalize=None) -> Callable:
    """Build `eval_step(state, batch) -> {loss_sum, correct, count}` —
    in-graph masked sums; the host just adds them across batches
    (trainer/metrics.py), nothing to gather.

    Multi-view eval (reference uniform-sampler tiling, run.py:163): when the
    clip leaves carry a view axis — (B, V, T, H, W, C) from a
    `num_clips > 1` source — `multiview_logits` folds the views into the
    batch for the forward pass and view-averages the logits in-graph before
    the argmax (the same helper the serving engine forwards through)."""

    def eval_step(state: TrainState, batch: dict) -> dict:
        batch = _constrain_batch(batch, mesh, leading_micro=False)
        batch = device_normalize_batch(batch, device_normalize)
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(batch["label"].shape, jnp.float32)
        # score the EMA weights when the state carries them (the recipes'
        # eval convention); BN stats stay the live ones
        eval_params = (state.ema_params if state.ema_params is not None
                       else state.params)
        logits = multiview_logits(
            lambda x: model.apply(
                {"params": eval_params, "batch_stats": state.batch_stats},
                x,
                train=False,
            ),
            model_inputs(batch),
        )
        loss, correct, count = _loss_and_metrics(
            logits, batch["label"], mask, label_smoothing
        )
        return {"loss_sum": loss * count, "correct": correct,
                "correct5": _topk_correct(logits, batch["label"], mask),
                "count": count}

    return jax.jit(eval_step)
