"""Training runtime: state pytree, optimizer, compiled steps, metrics,
checkpointing, tracking, and the epoch loop.

This package is the TPU-native replacement for the reference's L5 training
app (run.py:121-325) plus the slices of accelerate it delegates to
(SURVEY §2.2): instead of Accelerator verbs mutating torch objects, training
is a pure `TrainState -> TrainState` compiled step driven by a thin host loop.
"""

from pytorchvideo_accelerate_tpu.trainer.train_state import TrainState  # noqa: F401
from pytorchvideo_accelerate_tpu.trainer.optim import build_optimizer, build_lr_schedule  # noqa: F401
from pytorchvideo_accelerate_tpu.trainer.steps import (  # noqa: F401
    make_eval_step,
    make_pretrain_step,
    make_train_step,
)
