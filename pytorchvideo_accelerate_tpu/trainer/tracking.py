"""Experiment tracking multiplexer.

Replaces accelerate's tracker stack (SURVEY §2.2-A9: `GeneralTracker` ABC,
TensorBoard/wandb concrete trackers, `log_with="all"` auto-discovery at
tracking.py:1260-1290, main-process fan-out at accelerator.py:3356-3386).
Same shape here: a small Tracker protocol, concrete writers, and "all"
resolving to whatever is importable — wandb is absent in this image, so it
gates cleanly; tensorboard writes via tf.summary; jsonl is always available
and is what the bench/driver parse.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from pytorchvideo_accelerate_tpu.reliability.faults import fault_point
from pytorchvideo_accelerate_tpu.reliability.retry import retry_call
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

logger = get_logger("pva_tpu")


class Tracker:
    name = "base"

    def start(self, run_name: str, config: dict) -> None:  # pragma: no cover
        raise NotImplementedError

    def log(self, values: Dict[str, float], step: int) -> None:  # pragma: no cover
        raise NotImplementedError

    def finish(self) -> None:
        pass


class JsonlTracker(Tracker):
    """One JSON line per log call — always available, trivially parseable."""

    name = "jsonl"

    def __init__(self, logging_dir: str):
        self.logging_dir = logging_dir
        self._fh = None

    def start(self, run_name: str, config: dict) -> None:
        os.makedirs(self.logging_dir, exist_ok=True)
        path = os.path.join(self.logging_dir, f"{run_name}.jsonl")
        self._fh = open(path, "a")
        self._fh.write(json.dumps({"event": "start", "run": run_name,
                                   "time": time.time(), "config": config},
                                  default=str) + "\n")
        self._fh.flush()

    def log(self, values: Dict[str, float], step: int) -> None:
        if self._fh:
            self._fh.write(json.dumps({"step": int(step), **{k: float(v) for k, v in values.items()}}) + "\n")
            self._fh.flush()

    def finish(self) -> None:
        if self._fh:
            self._fh.write(json.dumps({"event": "end", "time": time.time()}) + "\n")
            self._fh.close()
            self._fh = None


class TensorBoardTracker(Tracker):
    name = "tensorboard"

    def __init__(self, logging_dir: str):
        self.logging_dir = logging_dir
        self._writer = None

    def start(self, run_name: str, config: dict) -> None:
        import tensorflow as tf  # installed in the build env

        self._writer = tf.summary.create_file_writer(
            os.path.join(self.logging_dir, run_name)
        )
        with self._writer.as_default():
            tf.summary.text("config", json.dumps(config, default=str), step=0)

    def log(self, values: Dict[str, float], step: int) -> None:
        import tensorflow as tf

        if self._writer:
            with self._writer.as_default():
                for k, v in values.items():
                    tf.summary.scalar(k, float(v), step=int(step))
            self._writer.flush()

    def finish(self) -> None:
        if self._writer:
            self._writer.close()
            self._writer = None


class WandbTracker(Tracker):
    name = "wandb"

    def __init__(self, logging_dir: str):
        self.logging_dir = logging_dir
        self._run = None

    def start(self, run_name: str, config: dict) -> None:
        import wandb

        self._run = wandb.init(name=run_name, config=config, dir=self.logging_dir)

    def log(self, values: Dict[str, float], step: int) -> None:
        if self._run:
            self._run.log(values, step=int(step))

    def finish(self) -> None:
        if self._run:
            self._run.finish()
            self._run = None


def _available(name: str) -> bool:
    if name == "jsonl":
        return True
    try:
        __import__({"tensorboard": "tensorflow", "wandb": "wandb"}[name])
        return True
    except Exception:
        return False


def resolve_trackers(spec: str, logging_dir: str) -> List[Tracker]:
    """`"all"` -> every importable tracker (accelerate tracking.py:1260-1290
    semantics); else a comma-list of names."""
    names = ["jsonl", "tensorboard", "wandb"] if spec == "all" else [
        s.strip() for s in spec.split(",") if s.strip()
    ]
    out: List[Tracker] = []
    for n in names:
        if not _available(n):
            logger.info("tracker %s unavailable; skipping", n)
            continue
        cls = {"jsonl": JsonlTracker, "tensorboard": TensorBoardTracker,
               "wandb": WandbTracker}[n]
        out.append(cls(logging_dir))
    return out


@shared_state("trackers")
class TrackerHub:
    """Fan-out facade: `init_trackers`/`log`/`end_training` equivalents
    (reference run.py:231,274,323). Construct on the main process only.

    Fan-out is NON-FATAL and RETRIED: a raising tracker (broken
    tensorboard install, wandb network hiccup, full disk under the jsonl
    file) gets `retries` total attempts with short backoff
    (reliability/retry.py — tracker outages are usually transient), and
    only an exhausted budget disables it — a logging failure must never
    kill a training step, and a blip must not cost the rest of the run's
    metrics. The surviving trackers keep logging.

    The disable path REBINDS `self.trackers` under a lock instead of
    mutating the live list: `log()` is called from the train loop and from
    serving/metric threads, and pva-tpu-tsan flagged the old bare
    `list.remove` racing a concurrent fan-out's iteration copy — two
    threads disabling at once could resurrect a just-removed tracker."""

    def __init__(self, spec: str, logging_dir: str, retries: int = 2):
        self._lock = make_lock("TrackerHub._lock")
        self.trackers = resolve_trackers(spec, logging_dir)
        self.retries = max(int(retries), 1)

    def _fanout(self, op: str, fn) -> None:
        with self._lock:
            trackers = list(self.trackers)
        for t in trackers:
            def attempt(t=t):
                # chaos hook: an injected raise exercises exactly the
                # retry-then-disable path a real tracker outage takes
                fault_point("tracker.log")
                fn(t)

            try:
                retry_call(attempt, name=f"tracker.{op}",
                           attempts=self.retries, retry_on=(Exception,),
                           base_delay_s=0.02, max_delay_s=0.25,
                           deadline_s=2.0)
            except Exception as e:  # noqa: BLE001 - any tracker bug qualifies
                logger.warning(
                    "tracker %r raised in %s (%s: %s) after %d attempt(s); "
                    "disabling it — a logging failure must never kill a "
                    "training step",
                    t.name, op, type(e).__name__, e, self.retries)
                with self._lock:
                    self.trackers = [x for x in self.trackers if x is not t]
                try:
                    from pytorchvideo_accelerate_tpu.obs import get_recorder

                    get_recorder().warn(f"tracker {t.name} disabled",
                                        op=op, error=str(e)[:200])
                except Exception:  # pragma: no cover - obs must stay optional
                    pass

    def start(self, run_name: str, config: dict) -> None:
        self._fanout("start", lambda t: t.start(run_name, config))

    def log(self, values: Dict[str, float], step: int) -> None:
        self._fanout("log", lambda t: t.log(values, step))

    def finish(self) -> None:
        self._fanout("finish", lambda t: t.finish())


class DeferredStepLogger:
    """One-step-delayed metric logging off the dispatch critical path.

    `float(metrics["loss"])` at `log_every` inside the step loop blocks the
    host on the CURRENT step's result before the next one can dispatch —
    exactly the sync the async-dispatch design works to avoid. Instead,
    `defer()` stashes the device scalars (kicking off their D2H copies
    asynchronously where the backend supports it) and `flush()` — called on
    the NEXT loop iteration, after another step has been dispatched — turns
    them into floats. By then the deferred step has all but certainly
    retired, so the fetch is a cache read, not a pipeline stall; at worst it
    blocks one step later than the old code did, never on the step just
    dispatched.

    Stash-then-flush also means at most one pending log at a time: a second
    `defer()` before `flush()` flushes the first (never silently drops it).
    """

    def __init__(self, hub: TrackerHub, on_flush=None):
        self.hub = hub
        # optional observer of the flushed floats (the obs layer mirrors
        # grad/param-norm gauges + the non-finite counter into the metric
        # registry here, off the dispatch critical path)
        self.on_flush = on_flush
        self._pending: Optional[tuple] = None

    def defer(self, values: Dict[str, object], step: int) -> None:
        if self._pending is not None:
            self.flush()
        for v in values.values():
            start = getattr(v, "copy_to_host_async", None)
            if start is not None:
                try:  # best-effort: a plain float has nothing to start
                    start()
                except Exception:  # pragma: no cover - backend-dependent
                    pass
        self._pending = (values, step)

    def flush(self) -> None:
        """Fetch + log the stashed metrics, if any (loop iteration top and
        epoch end both call this; safe to call with nothing pending)."""
        if self._pending is None:
            return
        values, step = self._pending
        self._pending = None
        floats = {k: float(v) for k, v in values.items()}
        if self.on_flush is not None:
            try:
                self.on_flush(floats, step)
            except Exception:  # observability must not kill the step loop
                pass
        self.hub.log(floats, step=step)
