"""The training state pytree.

Everything the reference's `accelerator.save_state` collects across torch
objects (model weights, optimizer state, scheduler counter, GradScaler —
accelerate checkpointing.py:63-180) lives here in one explicit pytree: params,
BN running stats, optax state (which embeds the schedule step), and the step
counter. No scaler (bf16 needs none), no scheduler object (the schedule is a
pure function of the step embedded in the optax chain).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray  # int32 scalar: optimizer steps taken
    params: Any
    batch_stats: Any
    opt_state: Any
    # exponential moving average of params (None = EMA off). Created as a
    # copy of the init params when `--optim.ema_decay > 0`; updated
    # in-graph each step; evaluation scores the EMA weights when present
    # (the MViT/VideoMAE fine-tune recipes' convention). Rides the
    # checkpoint pytree like every other field — same-config round trips
    # restore it; toggling EMA across a resume changes the tree structure
    # and fails loudly rather than silently dropping state.
    ema_params: Any = None

    @classmethod
    def create(cls, params, batch_stats, tx, ema: bool = False) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=tx.init(params),
            ema_params=jax.tree.map(jnp.copy, params) if ema else None,
        )
