"""Host-side metric accumulators.

Replaces torchmetrics' stateful `Accuracy` over gathered predictions
(reference run.py:236,298,303-304): the compiled eval step already returns
global masked sums, so the host accumulator is trivial arithmetic — and
bias-free under padding (SURVEY §2.1 eval-gather quirk).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np


@dataclass
class SumMetrics:
    """Accumulates {loss_sum, correct, count} dicts from eval steps.

    `update` keeps the device scalars un-fetched (same async-dispatch
    treatment as MeanLoss): the eval loop keeps dispatching batches while
    earlier ones execute, and the transfers happen in one batched
    `device_get` when a result is read.
    """

    loss_sum: float = 0.0
    correct: float = 0.0
    correct5: float = 0.0
    count: float = 0.0
    pending: list = field(default_factory=list)

    def update(self, step_out: dict) -> None:
        self.pending.append(step_out)

    def _drain(self) -> None:
        if self.pending:
            for out in jax.device_get(self.pending):
                self.loss_sum += float(out["loss_sum"])
                self.correct += float(out["correct"])
                self.correct5 += float(out.get("correct5", 0.0))
                self.count += float(out["count"])
            self.pending = []

    def accuracy(self) -> float:
        self._drain()
        return self.correct / max(self.count, 1.0)

    def accuracy_top5(self) -> float:
        self._drain()
        return self.correct5 / max(self.count, 1.0)

    def mean_loss(self) -> float:
        self._drain()
        return self.loss_sum / max(self.count, 1.0)

    def reset(self) -> None:
        self.loss_sum = self.correct = self.correct5 = self.count = 0.0
        self.pending = []


@dataclass
class MeanLoss:
    """Running epoch-mean train loss (reference `total_loss` run.py:239,269).

    `update_async` keeps device scalars un-fetched so the train loop never
    blocks on a step's result before dispatching the next; the transfers
    happen in one batched `device_get` at `mean()` (epoch end).
    """

    total: float = 0.0
    n: int = 0
    pending: list = field(default_factory=list)

    def update(self, loss) -> None:
        self.total += float(loss)
        self.n += 1

    def update_async(self, loss) -> None:
        self.pending.append(loss)

    def _drain(self) -> None:
        if self.pending:
            for v in jax.device_get(self.pending):
                self.total += float(v)
                self.n += 1
            self.pending = []

    def mean(self) -> float:
        self._drain()
        return self.total / max(self.n, 1)

    def reset(self) -> None:
        self.total, self.n, self.pending = 0.0, 0, []
