"""Optimizer + LR schedule.

Reference semantics (run.py:192-195): SGD(lr, momentum, weight_decay) over
*all* params (torch applies weight decay to BN scales/biases too — matched
here for parity), CosineAnnealingLR with
T_max = len(train_loader) * num_epochs // grad_accum.

One conscious fix (SURVEY §2.1 quirks): the reference's scheduler advances
`num_processes` steps per optimizer step (accelerate scheduler.py:69-79
compensating for world-sharded epoch length), so its cosine effectively
completes in 1/world of training. Here the schedule is a pure function of
the optimizer step and T_max counts *optimizer steps over the global batch* —
the cosine spans exactly the whole run regardless of world size.

freeze_backbone (run.py:108,116 `blocks[:-1].requires_grad_(False)`) is optax
`multi_transform`: backbone params get `set_to_zero`, head params the real
optimizer — gradients still flow (XLA DCEs the dead backward slices).
"""

from __future__ import annotations

from typing import Callable, Optional

import optax

from pytorchvideo_accelerate_tpu.config import OptimConfig


def build_lr_schedule(cfg: OptimConfig, total_steps: int) -> optax.Schedule:
    """Cosine annealing to 0 (CosineAnnealingLR eta_min=0 default) with
    optional linear warmup; or constant."""
    total_steps = max(int(total_steps), 1)
    if cfg.schedule == "constant":
        base = optax.constant_schedule(cfg.lr)
    elif cfg.schedule == "cosine":
        decay_steps = max(total_steps - cfg.warmup_steps, 1)
        base = optax.cosine_decay_schedule(cfg.lr, decay_steps=decay_steps, alpha=0.0)
    else:
        raise ValueError(f"unknown schedule {cfg.schedule!r}")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, cfg.lr, cfg.warmup_steps)
        return optax.join_schedules([warmup, base], [cfg.warmup_steps])
    return base


def build_optimizer(
    cfg: OptimConfig,
    total_steps: int,
    backbone_filter: Optional[Callable] = None,
    freeze_backbone: bool = False,
) -> optax.GradientTransformation:
    """SGD+momentum+wd+cosine by default; adamw for the transformer family.

    `backbone_filter(path) -> bool` marks backbone params; with
    `freeze_backbone=True` those get a zero update.
    """
    schedule = build_lr_schedule(cfg, total_steps)
    if cfg.optimizer == "sgd":
        # torch coupled weight decay: grad + wd*param, then momentum.
        tx = optax.chain(
            optax.add_decayed_weights(cfg.weight_decay),
            optax.sgd(learning_rate=schedule, momentum=cfg.momentum),
        )
    elif cfg.optimizer == "adamw":
        tx = optax.adamw(learning_rate=schedule, weight_decay=cfg.weight_decay)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")

    if cfg.grad_clip_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), tx)

    if freeze_backbone and backbone_filter is not None:
        def label(params):
            import jax

            return jax.tree_util.tree_map_with_path(
                lambda path, _: "frozen"
                if backbone_filter(tuple(_key_name(k) for k in path))
                else "trained",
                params,
            )

        tx = optax.multi_transform(
            {"trained": tx, "frozen": optax.set_to_zero()}, label
        )
    return tx


def _key_name(key) -> str:
    return getattr(key, "key", getattr(key, "name", str(key)))


def lr_at(cfg: OptimConfig, total_steps: int, step) -> float:
    """Current learning rate for logging (reference run.py:271 reads
    optimizer.param_groups[0]['lr']; here the schedule is pure)."""
    return build_lr_schedule(cfg, total_steps)(step)
