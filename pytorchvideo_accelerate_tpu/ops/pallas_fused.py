"""Fused conv -> norm-affine -> activation kernels for the 3D-CNN hot paths.

ROADMAP item 1 ("raw speed"): the slowfast/x3d residual blocks — the
dominant FLOPs of the headline `slowfast_r50` recipe — run today as
unfused XLA ops: conv, then a BatchNorm normalize pass, then an
activation pass, each a round trip over the activation tensor in HBM.
This module collapses the chain into single kernels behind the
`model.fused_kernels` knob (models/common.py wires them; off = today's
graph, byte for byte):

- `fused_pointwise_bn_act` — (1,1,1) conv + per-channel affine + act.
  A pointwise NDHWC conv IS a matmul over (B*T*H*W, Cin); the Pallas
  kernel tiles the row dim, accumulates on the MXU in f32, and applies
  bias + activation in the epilogue before the single cast-and-store.
- `fused_conv3d_bn_act` — dense small-kernel stride-1 SAME conv
  ((kt,1,1) temporal, (1,3,3) spatial, any odd kt/kh/kw) + affine +
  act. The halo-tile lowering of ops/pallas_depthwise.py generalized to
  channel-mixing convs: the grid tiles the OUTPUT over (batch, t, h),
  each program DMAs ONE overlapping input window (tile + (k-1)-halo,
  full W and Cin) HBM->VMEM, then runs the kt*kh*kw taps as MXU
  matmuls against a single f32 VMEM accumulator — input crosses
  HBM->VMEM once per tile, the output is written once, already
  normalized and activated.
- `fused_depthwise_bn_act` — the x3d conv_b / csn / stem_t depthwise
  chain: the halo kernel with the BN affine folded into the per-channel
  taps and bias + activation in the epilogue (VPU path, no MXU).

Norm-affine contract: callers pass the RESOLVED per-channel (scale,
bias) — for BatchNorm that is `scale = gamma * rsqrt(var + eps)`,
`bias = beta - mean * scale` (running stats at eval/serve time, batch
stats in training — models/common.BNAffine computes both). The scale
half folds into the conv WEIGHTS (`w * scale` commutes with the
channel-linear conv), so the kernels only carry a bias + act epilogue;
GroupNorm/LayerNorm affines fold the same way.

Backend dispatch (`mode`): "auto" lowers to the Pallas kernels on TPU
and to `_xla_*` — the scale-folded conv + bias + act formulation XLA
fuses well — everywhere else; interpret-mode Pallas is a PARITY tool,
never a production CPU path. "pallas"/"xla" force a lowering (kbench
A/Bs them; graphcheck traces the forced-pallas graph so the
registered-FLOPs hooks in analysis/gc_flops.py are exercised off-TPU).

Training: every Pallas path carries a `jax.custom_vjp` — dx reuses the
SAME kernel (stride-1 transpose conv = correlation with the
tap-flipped, channel-transposed weights), dw is per-tap strided
contractions XLA fuses, dbias a sum; act' is recomputed from the
pre-activation (one extra kernel pass instead of a saved residual —
the remat trade the rest of the stack already makes). The XLA mode is
plain autodiff. Parity against `jax.grad` of the unfused reference is
asserted in tests/test_zkernels.py and at kbench time.

Precision: accumulation and the bias/act epilogue run in deliberate
f32 islands (`precision.f32_island`; allowlisted by qualname in
analysis/gc_dtype.py), with ONE `precision.end_island` downcast to the
compute dtype at the store.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorchvideo_accelerate_tpu.ops.depthwise import depthwise_conv3d_shift
from pytorchvideo_accelerate_tpu.ops.pallas_depthwise import (
    _pad_for_tiles,
    _tile_sizes,
)
from pytorchvideo_accelerate_tpu.precision import end_island, f32_island

# the epilogues the model graph actually uses (nn.relu, nn.swish/silu,
# and the act=None projection convs); static strings so the jit cache
# keys stay hashable and each kernel specializes once
FUSED_ACTS = ("identity", "relu", "silu")


def apply_act(x, act: str):
    """Epilogue activation on the f32 accumulator (shared by the Pallas
    kernels, the XLA lowering, and the kbench references)."""
    if act == "relu":
        return jnp.maximum(x, 0.0)
    if act == "silu":
        return jax.nn.silu(x)
    if act == "identity":
        return x
    raise ValueError(f"fused act must be one of {FUSED_ACTS}, got {act!r}")


def _act_grad(z32, act: str):
    """d act/dz at the (f32) pre-activation z."""
    if act == "relu":
        return (z32 > 0).astype(z32.dtype)
    if act == "silu":
        s = jax.nn.sigmoid(z32)
        return s * (1.0 + z32 * (1.0 - s))
    return jnp.ones_like(z32)


def _use_pallas(mode: str) -> bool:
    if mode == "pallas":
        return True
    if mode == "xla":
        return False
    if mode != "auto":
        raise ValueError(f"fused mode must be auto|pallas|xla, got {mode!r}")
    return jax.default_backend() == "tpu"


def _interp(interpret: Optional[bool]) -> bool:
    # non-TPU backends run the identical kernel code interpreted so the
    # CPU harness unit-tests the real path (pallas_depthwise convention)
    return jax.default_backend() != "tpu" if interpret is None else interpret


# --- pointwise (1,1,1): tiled matmul + epilogue -----------------------------


def _pw_bn_act_kernel(x_ref, w_ref, b_ref, o_ref, *, act: str):
    # one MXU matmul per row tile, f32 accumulation, epilogue in f32
    acc = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    acc = apply_act(acc + f32_island(b_ref[0]), act)
    o_ref[:] = acc.astype(o_ref.dtype)


def _pw_call(x2d, w, b2d, act: str, interpret: bool):
    m, cin = x2d.shape
    cout = w.shape[-1]
    bm = min(256, -(-m // 8) * 8)
    pad = (-m) % bm
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_pw_bn_act_kernel, act=act),
        out_shape=jax.ShapeDtypeStruct((m + pad, cout), x2d.dtype),
        grid=((m + pad) // bm,),
        in_specs=[
            pl.BlockSpec((bm, cin), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, cout), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d, w, b2d)
    return out[:m] if pad else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _pw_pallas(x2d, wf, b2d, act: str, interpret: bool):
    """act((x2d @ wf) + bias) over (M, Cin) rows; wf is scale-folded."""
    return _pw_call(x2d, wf, b2d, act, interpret)


def _pw_fwd(x2d, wf, b2d, act, interpret):
    return _pw_call(x2d, wf, b2d, act, interpret), (x2d, wf, b2d)


def _pw_bwd(act, interpret, res, g):
    x2d, wf, b2d = res
    # recompute the pre-activation (remat instead of a saved residual)
    z32 = f32_island(_pw_call(x2d, wf, b2d, "identity", interpret))
    dz32 = f32_island(g) * _act_grad(z32, act)
    dz = end_island(dz32, x2d.dtype)
    # dx: the same tiled-matmul kernel against the transposed weights
    zeros = jnp.zeros((1, wf.shape[0]), jnp.float32)
    dx = _pw_call(dz, wf.T, zeros, "identity", interpret)
    dwf = end_island(
        jnp.einsum("mc,md->cd", f32_island(x2d), dz32), wf.dtype)
    db = jnp.sum(dz32, axis=0, keepdims=True)
    return dx, dwf, db


_pw_pallas.defvjp(_pw_fwd, _pw_bwd)


# --- dense small-kernel stride-1 SAME conv + epilogue -----------------------


def _conv_bn_act_kernel(x_hbm, w_ref, b_ref, o_ref, win_ref, sem, *,
                        tb: int, hb: int, ow: int,
                        kt: int, kh: int, kw: int, act: str):
    b = pl.program_id(0)
    ti = pl.program_id(1)
    hi = pl.program_id(2)
    # one DMA: the output tile's input window incl. halo (full W, full Cin)
    dma = pltpu.make_async_copy(
        x_hbm.at[b, pl.ds(ti * tb, tb + kt - 1),
                 pl.ds(hi * hb, hb + kh - 1)],
        win_ref, sem)
    dma.start()
    dma.wait()

    cin = win_ref.shape[-1]
    cout = o_ref.shape[-1]
    rows = tb * hb * ow
    acc = jnp.zeros((rows, cout), jnp.float32)
    for dt in range(kt):
        for dh in range(kh):
            for dw in range(kw):
                tap = win_ref[dt:dt + tb, dh:dh + hb, dw:dw + ow, :]
                acc += jnp.dot(tap.reshape(rows, cin),
                               w_ref[(dt * kh + dh) * kw + dw],
                               preferred_element_type=jnp.float32)
    acc = apply_act(acc + f32_island(b_ref[0]), act)
    o_ref[0] = acc.reshape(tb, hb, ow, cout).astype(o_ref.dtype)


def _conv_call(x, wf, b2d, act: str, interpret: bool):
    kt, kh, kw, cin, cout = wf.shape
    b, t, h, w, _ = x.shape
    tb, hb = _tile_sizes(t, h)
    xp = _pad_for_tiles(x, kt, kh, kw, tb, hb)
    wp = xp.shape[3]
    n_t = -(-t // tb)
    n_h = -(-h // hb)
    wflat = wf.reshape(kt * kh * kw, cin, cout)
    return pl.pallas_call(
        functools.partial(_conv_bn_act_kernel, tb=tb, hb=hb, ow=w,
                          kt=kt, kh=kh, kw=kw, act=act),
        out_shape=jax.ShapeDtypeStruct((b, t, h, w, cout), x.dtype),
        grid=(b, n_t, n_h),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((kt * kh * kw, cin, cout),
                         lambda bi, ti, hi: (0, 0, 0)),
            pl.BlockSpec((1, cout), lambda bi, ti, hi: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tb, hb, w, cout),
                               lambda bi, ti, hi: (bi, ti, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tb + kt - 1, hb + kh - 1, wp, cin), xp.dtype),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(xp, wflat, b2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _conv_pallas(x, wf, b2d, act: str, interpret: bool):
    """act(conv3d_s1(x, wf) + bias), SAME k//2 padding; wf scale-folded."""
    return _conv_call(x, wf, b2d, act, interpret)


def _conv_fwd(x, wf, b2d, act, interpret):
    return _conv_call(x, wf, b2d, act, interpret), (x, wf, b2d)


def _conv_bwd(act, interpret, res, g):
    x, wf, b2d = res
    kt, kh, kw, cin, cout = wf.shape
    z32 = f32_island(_conv_call(x, wf, b2d, "identity", interpret))
    dz32 = f32_island(g) * _act_grad(z32, act)
    dz = end_island(dz32, x.dtype)
    # dx: correlation with the tap-flipped, channel-transposed weights —
    # the stride-1 transpose conv is the same stencil, so the same kernel
    wt = wf[::-1, ::-1, ::-1].transpose(0, 1, 2, 4, 3)
    zeros = jnp.zeros((1, cin), jnp.float32)
    dx = _conv_call(dz, wt, zeros, "identity", interpret)
    # dw: per-tap contractions over the padded input — plain jnp, XLA fuses
    xp = jnp.pad(x, ((0, 0), (kt // 2, kt // 2), (kh // 2, kh // 2),
                     (kw // 2, kw // 2), (0, 0)))
    t, h, w = dz.shape[1:4]
    taps = []
    for dt in range(kt):
        for dh in range(kh):
            for dw in range(kw):
                win = xp[:, dt:dt + t, dh:dh + h, dw:dw + w, :]
                taps.append(jnp.einsum("bthwc,bthwd->cd",
                                       f32_island(win), dz32))
    dwf = end_island(jnp.stack(taps).reshape(kt, kh, kw, cin, cout),
                     wf.dtype)
    db = jnp.sum(dz32, axis=(0, 1, 2, 3))[None, :]
    return dx, dwf, db


_conv_pallas.defvjp(_conv_fwd, _conv_bwd)


# --- depthwise + epilogue ---------------------------------------------------


def _dw_bn_act_kernel(x_hbm, k_ref, b_ref, o_ref, win_ref, sem, *,
                      tb: int, hb: int, ow: int,
                      kt: int, kh: int, kw: int, act: str):
    b = pl.program_id(0)
    ti = pl.program_id(1)
    hi = pl.program_id(2)
    dma = pltpu.make_async_copy(
        x_hbm.at[b, pl.ds(ti * tb, tb + kt - 1),
                 pl.ds(hi * hb, hb + kh - 1)],
        win_ref, sem)
    dma.start()
    dma.wait()

    acc = jnp.zeros(o_ref.shape[1:], jnp.float32)  # (tb, hb, ow, C)
    for dt in range(kt):
        for dh in range(kh):
            for dw in range(kw):
                tap = win_ref[dt:dt + tb, dh:dh + hb, dw:dw + ow, :]
                acc += f32_island(tap) * f32_island(
                    k_ref[(dt * kh + dh) * kw + dw])
    acc = apply_act(acc + f32_island(b_ref[0]), act)
    o_ref[0] = acc.astype(o_ref.dtype)


def _dw_call(x, kf, b2d, act: str, interpret: bool):
    kt, kh, kw, one, c = kf.shape
    b, t, h, w, _ = x.shape
    tb, hb = _tile_sizes(t, h)
    xp = _pad_for_tiles(x, kt, kh, kw, tb, hb)
    wp = xp.shape[3]
    n_t = -(-t // tb)
    n_h = -(-h // hb)
    kflat = kf.reshape(kt * kh * kw, c)
    return pl.pallas_call(
        functools.partial(_dw_bn_act_kernel, tb=tb, hb=hb, ow=w,
                          kt=kt, kh=kh, kw=kw, act=act),
        out_shape=jax.ShapeDtypeStruct((b, t, h, w, c), x.dtype),
        grid=(b, n_t, n_h),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((kt * kh * kw, c), lambda bi, ti, hi: (0, 0)),
            pl.BlockSpec((1, c), lambda bi, ti, hi: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tb, hb, w, c),
                               lambda bi, ti, hi: (bi, ti, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tb + kt - 1, hb + kh - 1, wp, c), xp.dtype),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(xp, kflat, b2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _dw_pallas(x, kf, b2d, act: str, interpret: bool):
    """act(depthwise_conv3d_s1(x, kf) + bias); kf (kt,kh,kw,1,C)
    scale-folded."""
    return _dw_call(x, kf, b2d, act, interpret)


def _dw_fwd(x, kf, b2d, act, interpret):
    return _dw_call(x, kf, b2d, act, interpret), (x, kf, b2d)


def _dw_bwd(act, interpret, res, g):
    x, kf, b2d = res
    kt, kh, kw = kf.shape[:3]
    z32 = f32_island(_dw_call(x, kf, b2d, "identity", interpret))
    dz32 = f32_island(g) * _act_grad(z32, act)
    dz = end_island(dz32, x.dtype)
    zeros = jnp.zeros((1, kf.shape[-1]), jnp.float32)
    dx = _dw_call(dz, kf[::-1, ::-1, ::-1], zeros, "identity", interpret)
    xp = jnp.pad(x, ((0, 0), (kt // 2, kt // 2), (kh // 2, kh // 2),
                     (kw // 2, kw // 2), (0, 0)))
    t, h, w = dz.shape[1:4]
    rows = []
    for dt in range(kt):
        for dh in range(kh):
            for dw in range(kw):
                tap = xp[:, dt:dt + t, dh:dh + h, dw:dw + w, :]
                rows.append(jnp.sum(f32_island(tap) * dz32,
                                    axis=(0, 1, 2, 3)))
    dkf = end_island(jnp.stack(rows).reshape(kt, kh, kw, 1, -1), kf.dtype)
    db = jnp.sum(dz32, axis=(0, 1, 2, 3))[None, :]
    return dx, dkf, db


_dw_pallas.defvjp(_dw_fwd, _dw_bwd)


# --- XLA lowerings (the production non-TPU path; also autodiff-plain) -------


def _xla_conv_bias_act(x, wf, bias32, act: str):
    """Scale-folded conv + bias + act as ONE fusable XLA chain — the
    `mode="xla"` lowering `mode="auto"` picks off-TPU."""
    y = lax.conv_general_dilated(
        x, wf, (1, 1, 1), [(k // 2, k // 2) for k in wf.shape[:3]],
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    return end_island(apply_act(f32_island(y) + bias32, act), x.dtype)


def _xla_dw_bias_act(x, kf, bias32, act: str):
    """Depthwise fold: the tap-decomposition lowering (ops/depthwise.py)
    with the affine folded in — the formulation that beats XLA's grouped
    conv by two orders of magnitude on CPU hosts (kbench measures it)."""
    y = depthwise_conv3d_shift(x, kf)
    return end_island(apply_act(f32_island(y) + bias32, act), x.dtype)


# --- public dispatchers -----------------------------------------------------


def fused_pointwise_bn_act(x, w, scale, bias, *, act: str = "identity",
                           mode: str = "auto",
                           interpret: Optional[bool] = None):
    """(1,1,1) conv + resolved norm affine + act. x: (B,T,H,W,Cin);
    w: (1,1,1,Cin,Cout) or (Cin,Cout); scale/bias: (Cout,) f32."""
    if w.ndim == 5:
        w = w.reshape(w.shape[-2], w.shape[-1])
    cin, cout = w.shape
    scale32, bias32 = f32_island(scale), f32_island(bias)
    wf = end_island(f32_island(w) * scale32, x.dtype)
    if not _use_pallas(mode):
        y = f32_island(x.reshape(-1, cin) @ wf) + bias32
        y = end_island(apply_act(y, act), x.dtype)
        return y.reshape(*x.shape[:-1], cout)
    y = _pw_pallas(x.reshape(-1, cin), wf, bias32[None, :], act,
                   _interp(interpret))
    return y.reshape(*x.shape[:-1], cout)


def fused_conv3d_bn_act(x, w, scale, bias, *, act: str = "identity",
                        mode: str = "auto",
                        interpret: Optional[bool] = None):
    """Dense stride-1 SAME conv + resolved norm affine + act.
    x: (B,T,H,W,Cin); w: (kt,kh,kw,Cin,Cout) odd taps; scale/bias:
    (Cout,) f32. (1,1,1) weights route to the pointwise matmul kernel;
    even-tap kernels fall back to the XLA lowering (the halo kernel
    hard-codes odd SAME geometry)."""
    kt, kh, kw = w.shape[:3]
    if (kt, kh, kw) == (1, 1, 1):
        return fused_pointwise_bn_act(x, w, scale, bias, act=act,
                                      mode=mode, interpret=interpret)
    scale32, bias32 = f32_island(scale), f32_island(bias)
    wf = end_island(f32_island(w) * scale32, x.dtype)
    if not _use_pallas(mode) or not all(k % 2 for k in (kt, kh, kw)):
        return _xla_conv_bias_act(x, wf, bias32, act)
    return _conv_pallas(x, wf, bias32[None, :], act, _interp(interpret))


def fused_depthwise_bn_act(x, k, scale, bias, *, act: str = "identity",
                           mode: str = "auto",
                           interpret: Optional[bool] = None):
    """Depthwise stride-1 SAME conv + resolved norm affine + act.
    x: (B,T,H,W,C); k: (kt,kh,kw,1,C) odd taps; scale/bias: (C,) f32.
    The per-channel affine scale folds into the per-channel taps."""
    scale32, bias32 = f32_island(scale), f32_island(bias)
    kf = end_island(f32_island(k) * scale32, x.dtype)
    if (not _use_pallas(mode)
            or not all(d % 2 for d in k.shape[:3])):
        return _xla_dw_bias_act(x, kf, bias32, act)
    return _dw_pallas(x, kf, bias32[None, :], act, _interp(interpret))
