"""Hand-tiled flash attention Pallas kernels for TPU — forward AND backward.

Escape hatch for sizes where XLA's default attention schedule underperforms
(SURVEY §7 hard-part 2: "Pallas kernels as escape hatch"). Forward is the
classic flash-attention recurrence laid out for the TPU memory hierarchy:

- grid (B·H, Nq/block_q, Nk/block_k); the last grid axis is sequential on a
  TensorCore, so VMEM scratch (acc/m/l) persists across K/V blocks of one
  query tile — HBM traffic is one pass over K/V per query tile and a single
  write of the output tile.
- QK^T and PV hit the MXU via `jnp.dot(..., preferred_element_type=f32)`;
  the online-softmax update (exp/max/sum) runs on the VPU in f32.
- m/l running stats live in (block_q, 128) VMEM tiles (lane-dim 128 is the
  minimum f32 tile; every lane carries the same value — broadcast storage
  sidesteps 1-D layout constraints).

Training works: a `jax.custom_vjp` pairs the forward with two backward
kernels (FlashAttention-2 style recomputation, Dao 2023 §3.2):
- forward additionally emits L = m + log(l) (the per-row logsumexp);
- dq kernel, grid (BH, nQ, nK): p = exp(s - L) recomputed blockwise,
  ds = p∘(dO·Vᵀ - Δ), dq += ds·K accumulated in VMEM scratch over K blocks;
- dk/dv kernel, grid (BH, nK, nQ): same recompute with the loop order
  flipped, dv += pᵀ·dO and dk += dsᵀ·Q accumulated over Q blocks;
- Δ = rowsum(dO ∘ O) is a cheap elementwise jnp precompute.

Numerics match `ops.attention.dense_attention` to f32 rounding: accumulation
is f32 regardless of input dtype (bf16 in, bf16 out, f32 inside).

On non-TPU backends the kernels run in interpreter mode so the same code
path is unit-testable on the 8-fake-CPU-device harness (SURVEY §4).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from pytorchvideo_accelerate_tpu.precision import f32_island
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the pinned jax 0.4.37 ships this as TPUCompilerParams; newer jax
# renamed it CompilerParams — accept either
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

NEG_INF = -1e30
LANES = 128  # broadcast width for per-row stats (min f32 lane tile)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                scale: float, nk_valid: int, block_k: int):
    ki = pl.program_id(2)
    nk_blocks = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0]                                     # (bq, D)
    k = k_ref[0]                                     # (bk, D)
    v = v_ref[0]
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale  # (bq, bk)

    # mask K padding (Nk rounded up to a block multiple)
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < nk_valid, s, NEG_INF)

    m_prev = m_ref[:, 0:1]                           # (bq, 1)
    l_prev = l_ref[:, 0:1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)                           # (bq, bk) f32
    alpha = jnp.exp(m_prev - m_new)                  # (bq, 1)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.dot(p.astype(v.dtype), v, preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk_blocks - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale: float, nk_valid: int, block_k: int):
    ki = pl.program_id(2)
    nk_blocks = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < nk_valid, s, NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, 0:1])              # (bq, bk); 0 for padding
    dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0][:, 0:1]) * scale     # (bq, bk) f32
    acc_ref[:] += jnp.dot(ds.astype(k.dtype), k, preferred_element_type=jnp.float32)

    @pl.when(ki == nk_blocks - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale: float, nk_valid: int, block_k: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq_blocks = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale  # (bq, bk)
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < nk_valid, s, NEG_INF)
    p = jnp.exp(s - lse_ref[0][:, 0:1])              # (bq, bk)
    dv_acc[:] += jnp.dot(p.astype(do.dtype).T, do, preferred_element_type=jnp.float32)
    dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0][:, 0:1]) * scale
    dk_acc[:] += jnp.dot(ds.astype(q.dtype).T, q, preferred_element_type=jnp.float32)

    @pl.when(qi == nq_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _pad_seq(x, block):
    pad = (-x.shape[1]) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _fwd_call(q, k, v, scale, block_q, block_k, interpret):
    BH, nq, D = q.shape
    nk = k.shape[1]
    q = _pad_seq(q, block_q)
    k = _pad_seq(k, block_k)
    v = _pad_seq(v, block_k)
    nq_p, nk_p = q.shape[1], k.shape[1]
    grid = (BH, nq_p // block_q, nk_p // block_k)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, nk_valid=nk, block_k=block_k),
        out_shape=[
            jax.ShapeDtypeStruct((BH, nq_p, D), q.dtype),
            jax.ShapeDtypeStruct((BH, nq_p, LANES), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, qi, ki: (b, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out[:, :nq], lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhnd(q, k, v, scale, block_q, block_k, interpret):
    """q/k/v: (BH, N, D) -> (BH, Nq, D)."""
    out, _ = _fwd_call(q, k, v, scale, block_q, block_k, interpret)
    return out


def _flash_bhnd_fwd(q, k, v, scale, block_q, block_k, interpret):
    out, lse = _fwd_call(q, k, v, scale, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bhnd_bwd(scale, block_q, block_k, interpret, res, dout):
    q, k, v, out, lse = res
    BH, nq, D = q.shape
    nk = k.shape[1]

    # Δ_i = Σ_d dO_id · O_id, broadcast over lanes for tiled VMEM access
    delta = jnp.broadcast_to(
        jnp.sum(f32_island(dout) * f32_island(out),
                axis=-1, keepdims=True),
        (BH, nq, LANES),
    )

    qp = _pad_seq(q, block_q)
    dop = _pad_seq(dout, block_q)
    lsep = _pad_seq(lse, block_q)
    deltap = _pad_seq(delta, block_q)
    kp = _pad_seq(k, block_k)
    vp = _pad_seq(v, block_k)
    nq_p, nk_p = qp.shape[1], kp.shape[1]
    # padded-q rows: lse is finite (they attended real keys in fwd) and
    # dout rows are zero, so their ds/dv contributions vanish

    q_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    stat_spec = pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, nk_valid=nk,
                          block_k=block_k),
        out_shape=jax.ShapeDtypeStruct((BH, nq_p, D), q.dtype),
        grid=(BH, nq_p // block_q, nk_p // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, stat_spec, stat_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    # loop order flipped: K/V block fixed per grid row, Q blocks stream
    q_spec2 = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0))
    stat_spec2 = pl.BlockSpec((1, block_q, LANES), lambda b, j, i: (b, i, 0))
    k_spec2 = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, nk_valid=nk,
                          block_k=block_k),
        out_shape=[
            jax.ShapeDtypeStruct((BH, nk_p, D), k.dtype),
            jax.ShapeDtypeStruct((BH, nk_p, D), v.dtype),
        ],
        grid=(BH, nk_p // block_k, nq_p // block_q),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, stat_spec2, stat_spec2],
        out_specs=[k_spec2, k_spec2],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    return dq[:, :nq], dk[:, :nk], dv[:, :nk]


_flash_bhnd.defvjp(_flash_bhnd_fwd, _flash_bhnd_bwd)


def flash_attention(q, k, v, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Flash attention, API-compatible with `dense_attention`; differentiable
    (custom VJP backed by Pallas backward kernels).

    q: (B, Nq, H, D); k/v: (B, Nkv, H, D) -> (B, Nq, H, D). Sequence lengths
    need not be block multiples (padded + masked internally). `interpret`
    defaults to True off-TPU so tests run on CPU.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, nq, H, D = q.shape
    nkv = k.shape[1]

    def fold(x):   # (B, N, H, D) -> (B*H, N, D)
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    out = _flash_bhnd(fold(q), fold(k), fold(v), float(scale),
                      min(block_q, _round_up(nq)), min(block_k, _round_up(nkv)),
                      bool(interpret))
    return out.reshape(B, H, nq, D).transpose(0, 2, 1, 3)


def _round_up(n: int, mult: int = 8) -> int:
    return ((n + mult - 1) // mult) * mult
