"""`pva-tpu-kbench`: attributable kernel microbenchmarks.

The missing link between "the bench number moved" and "because of THIS
kernel": each fused conv/norm/act kernel (ops/pallas_fused.py) is timed
against its unfused XLA reference — the exact op chain the model graph
runs with `model.fused_kernels=off` — at the REAL model shapes of the
slowfast_r50/x3d_s hot paths, and the per-kernel speedup keys ride the
bench headline so `pva-tpu-perfdiff` can attribute wins round over
round instead of guessing which change moved the trajectory.

Honesty rules (the bench.py house discipline):
- **parity before speed**: every case asserts fused-vs-reference
  allclose at the benched shape, AND interpret-mode Pallas parity at a
  reduced shape on non-TPU hosts (the kernels' unit-test contract) —
  a fast wrong kernel fails the lane, it does not headline;
- **same-backend ratios only**: `speedup` is reference-time /
  fused-time on ONE backend. On a TPU host that is the device story;
  on a CPU host it is an honest host story (the folded-shift depthwise
  lowering beats XLA:CPU's grouped conv by ~two orders of magnitude at
  x3d shapes) — the record carries `platform` and `device` so a CPU
  ratio can never impersonate a device number, per the standing
  suspect-round refusal rule; raw millisecond timings stay in
  bench_partial.json, never on the headline;
- the timing loop rotates two distinct inputs (the bench.py
  anti-constant-folding discipline) and syncs via value fetch.

Run it standalone (`pva-tpu-kbench [--smoke] [--json]`), through the
bench lane (`bench.py --kbench`, on by default), or from the analysis
gate (`scripts/analyze.sh` runs `--smoke`). Exit codes: 0 = parity
clean, 1 = parity violation, 2 = usage.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from dataclasses import dataclass
from typing import Callable, List, Optional


def _log(*a):
    print(*a, file=sys.stderr, flush=True)


@dataclass
class KernelCase:
    """One fused kernel vs its XLA reference at one model shape."""

    name: str            # headline key suffix: kbench_<name>_speedup
    attribution: str     # which model/block this shape comes from
    shape: tuple         # (B, T, H, W, C...) documentation
    ref: Callable        # unfused reference (the fused_kernels=off chain)
    fused: Callable      # ops/pallas_fused dispatcher, mode="auto"
    pallas: Callable     # forced-pallas variant (interpret off-TPU)
    args: tuple          # benched operands
    small_args: tuple    # reduced operands for interpret-mode parity
    rtol: float = 2e-5
    atol: float = 2e-5


def _affine(rng, c):
    """A realistic resolved BN affine (gamma/beta over running stats)."""
    import jax.numpy as jnp

    gamma = rng.standard_normal(c).astype("float32") * 0.1 + 1.0
    beta = rng.standard_normal(c).astype("float32") * 0.1
    mean = rng.standard_normal(c).astype("float32") * 0.1
    var = abs(rng.standard_normal(c)).astype("float32") + 1.0
    scale = gamma / (var + 1e-5) ** 0.5
    return jnp.asarray(scale), jnp.asarray(beta - mean * scale)


def build_cases(smoke: bool) -> List[KernelCase]:
    """The measured hot-path shapes. Geometry provenance:
    x3d_s samples 13f@160px -> stem 80 -> res2 40 -> res3 20 -> res4 10
    with inner widths 54/108/216/432 (expansion 2.25); slowfast_r50
    samples 32f@256px -> slow pathway 8f, res4 at 16x16 with inner 256.
    Smoke mode shrinks every case to harness-verification size."""
    import functools

    import jax.numpy as jnp
    import numpy as np

    from pytorchvideo_accelerate_tpu.ops.pallas_fused import (
        fused_conv3d_bn_act,
        fused_depthwise_bn_act,
        fused_pointwise_bn_act,
    )
    from pytorchvideo_accelerate_tpu.ops.kbench_refs import (
        ref_conv_bn_act,
        ref_dw_bn_act,
        ref_pw_bn_act,
    )

    rng = np.random.default_rng(0)
    cases: List[KernelCase] = []

    def clips(shape):
        return jnp.asarray(rng.standard_normal(shape), jnp.float32)

    def weights(shape, s=0.1):
        return jnp.asarray(rng.standard_normal(shape) * s, jnp.float32)

    def add(name, attribution, shape, ref, fused_fn, args, small_args,
            **kw):
        cases.append(KernelCase(
            name=name, attribution=attribution, shape=shape, ref=ref,
            fused=functools.partial(fused_fn, mode="auto"),
            pallas=functools.partial(fused_fn, mode="pallas"),
            args=args, small_args=small_args, **kw))

    # --- x3d_s res3 depthwise conv_b + BN + swish (the x3d FLOPs bound) -
    b, t, h, c = (1, 4, 8, 16) if smoke else (2, 13, 20, 108)
    x = clips((b, t, h, h, c))
    k = weights((3, 3, 3, 1, c))
    s_, bi = _affine(rng, c)
    xs = clips((1, 4, 6, 6, 8))
    ks = weights((3, 3, 3, 1, 8))
    ss, bs = _affine(rng, 8)
    add("dw_x3d_res3", "x3d_s res3 conv_b 3x3x3 dw + BN + swish",
        (b, t, h, h, c),
        functools.partial(ref_dw_bn_act, act="silu"),
        functools.partial(fused_depthwise_bn_act, act="silu"),
        (x, k, s_, bi), (xs, ks, ss, bs))

    # --- x3d_s res3 pointwise expand conv_a + BN + relu -----------------
    cin, cout = (8, 16) if smoke else (48, 108)
    x = clips((b, t, h, h, cin))
    w = weights((1, 1, 1, cin, cout))
    s_, bi = _affine(rng, cout)
    ws = weights((1, 1, 1, 8, 12))
    ss, bs = _affine(rng, 12)
    add("pw_x3d_res3", "x3d_s res3 conv_a 1x1x1 expand + BN + relu",
        (b, t, h, h, cin, cout),
        functools.partial(ref_pw_bn_act, act="relu"),
        functools.partial(fused_pointwise_bn_act, act="relu"),
        (x, w, s_, bi), (xs, ws, ss, bs))

    # --- slowfast_r50 slow res4 spatial conv_b (1,3,3) + BN + relu ------
    b2, t2, hw, cc = (1, 4, 8, 16) if smoke else (2, 8, 16, 256)
    x = clips((b2, t2, hw, hw, cc))
    w = weights((1, 3, 3, cc, cc), s=0.05)
    s_, bi = _affine(rng, cc)
    ws = weights((1, 3, 3, 8, 8))
    ss, bs = _affine(rng, 8)
    add("conv133_sf_res4", "slowfast_r50 slow res4 conv_b (1,3,3) + BN "
        "+ relu", (b2, t2, hw, hw, cc),
        functools.partial(ref_conv_bn_act, act="relu"),
        functools.partial(fused_conv3d_bn_act, act="relu"),
        (x, w, s_, bi), (xs, ws, ss, bs))

    # --- slowfast_r50 fast res4 temporal conv_a (3,1,1) + BN + relu -----
    cin3, cout3 = (16, 8) if smoke else (128, 32)
    t3 = 4 if smoke else 32
    x = clips((b2, t3, hw, hw, cin3))
    w = weights((3, 1, 1, cin3, cout3), s=0.05)
    s_, bi = _affine(rng, cout3)
    ws = weights((3, 1, 1, 8, 8))
    add("conv311_sf_res4", "slowfast_r50 fast res4 conv_a (3,1,1) + BN "
        "+ relu", (b2, t3, hw, hw, cin3),
        functools.partial(ref_conv_bn_act, act="relu"),
        functools.partial(fused_conv3d_bn_act, act="relu"),
        (x, w, s_, bi), (xs, ws, ss, bs))

    # --- streaming KV-trunk incremental attention (trunk-reuse win) -----
    # The per-layer attention the KV-ring advance runs (streaming/
    # engine.py `_trunk_kv_step`): the ONE new slot's queries against the
    # cached window K/V, vs the full-recompute baseline that re-attends
    # every window query. Real videomae_b stream shape: dim 768, 12
    # heads, T' = 8 token slots of hw = 196 spatial tokens, 1 new slot
    # per advance. The "pallas" lane is the einsum-dense lowering of the
    # SAME banded op (there is no pallas masked kernel) — genuine
    # cross-lowering parity for the band-mask arithmetic.
    from pytorchvideo_accelerate_tpu.ops.attention import (
        dense_attention,
        incremental_band_attention,
        temporal_band_mask,
    )

    def _band(kind, nslots):
        # band width from the (static) slot count, so one closure serves
        # the benched and the reduced interpret-parity shapes alike:
        # causal = every trailing slot; windowed = a quarter of them
        return nslots if kind == "causal" else max(2, nslots // 4)

    def _inc_attn(kind, q_all, k, v, q_slots, k_slots, mode="auto"):
        nslots = q_slots.shape[1]
        hw_ = q_all.shape[1] // nslots
        q_new = q_all[:, -hw_:]                       # ONE new slot
        return incremental_band_attention(
            q_new, k, v, q_slots[:, -1:], k_slots, _band(kind, nslots),
            hw_, impl=("dense" if mode == "pallas" else "fused"))

    def _inc_attn_ref(kind, q_all, k, v, q_slots, k_slots):
        # the full-recompute baseline: every slot's queries re-attend,
        # then only the new slot's rows are read out
        nslots = q_slots.shape[1]
        hw_ = q_all.shape[1] // nslots
        mask = temporal_band_mask(nslots, hw_,
                                  _band(kind, nslots))[None, None]
        return dense_attention(q_all, k, v, mask=mask)[:, -hw_:]

    heads, hd, tn, hw_a = (2, 8, 4, 4) if smoke else (12, 64, 8, 196)
    qkv_shape = (1, (tn + 1) * hw_a, heads, hd)
    q_all, kk, vv = clips(qkv_shape), clips(qkv_shape), clips(qkv_shape)
    slots = jnp.arange(tn + 1, dtype=jnp.int32)[None]
    qs, ks2, vs2 = (clips((1, 10, 2, 8)) for _ in range(3))
    sl_s = jnp.arange(5, dtype=jnp.int32)[None]
    for kind in ("causal", "windowed"):
        add(f"attn_{kind}_inc",
            f"videomae_b stream advance, {kind} band W="
            f"{_band(kind, tn + 1)} (T'={tn}, hw={hw_a}, 1 new slot)",
            (1, (tn + 1) * hw_a, heads, hd),
            functools.partial(_inc_attn_ref, kind),
            functools.partial(_inc_attn, kind),
            (q_all, kk, vv, slots, slots),
            (qs, ks2, vs2, sl_s, sl_s), rtol=2e-4, atol=2e-4)
    return cases


def _time_fn(fn, args, iters: int, warmup: int = 1) -> float:
    """Median wall ms per call, value-fetch synced; rotates two operand
    sets so a caching backend can't replay one result."""
    import jax
    import numpy as np

    rotated = [args, tuple(a * (1.0 + 1e-6) if hasattr(a, "dtype") else a
                           for a in args)]
    for i in range(warmup):
        jax.block_until_ready(fn(*rotated[i % 2]))
    samples = []
    for i in range(iters):
        t0 = time.perf_counter()
        out = fn(*rotated[i % 2])
        np.asarray(jax.tree_util.tree_leaves(out)[0])  # value-fetch sync
        samples.append(time.perf_counter() - t0)
    return statistics.median(samples) * 1e3


def run_kbench(smoke: bool = False, iters: Optional[int] = None,
               log=_log) -> dict:
    """Benchmark every case; returns the record bench.py headlines from."""
    import jax
    import numpy as np

    iters = iters if iters is not None else (3 if smoke else 7)
    platform = jax.default_backend()
    on_device = platform == "tpu"
    t_start = time.perf_counter()
    kernels = {}
    all_parity = True
    for case in build_cases(smoke):
        # one jit wrapper per benchmark case, reused for parity AND the
        # whole timing loop — the per-case compile IS the measurement unit
        ref_j = jax.jit(case.ref)      # pva: disable=recompile -- one compile per case, reused across the timing loop
        fused_j = jax.jit(case.fused)  # pva: disable=recompile -- one compile per case, reused across the timing loop
        # parity at the benched shape (fused "auto" lowering vs reference)
        got = np.asarray(fused_j(*case.args), np.float32)
        want = np.asarray(ref_j(*case.args), np.float32)
        parity = bool(np.allclose(got, want, rtol=case.rtol,
                                  atol=case.atol))
        # interpret-mode Pallas parity at the reduced shape (off-TPU the
        # auto lowering is folded-XLA, so this is what exercises the
        # actual kernel code); on TPU the benched fused fn IS pallas
        pal_got = np.asarray(case.pallas(*case.small_args), np.float32)
        pal_want = np.asarray(case.ref(*case.small_args), np.float32)
        interp_parity = bool(np.allclose(pal_got, pal_want,
                                         rtol=case.rtol, atol=case.atol))
        all_parity = all_parity and parity and interp_parity
        ms_ref = _time_fn(ref_j, case.args, iters)
        ms_fused = _time_fn(fused_j, case.args, iters)
        rec = {
            "attribution": case.attribution,
            "shape": list(case.shape),
            "ms_ref": round(ms_ref, 3),
            "ms_fused": round(ms_fused, 3),
            "speedup": round(ms_ref / max(ms_fused, 1e-9), 3),
            "parity_ok": parity,
            "interpret_parity_ok": interp_parity,
            "lowering": "pallas" if on_device else "xla-folded",
        }
        kernels[case.name] = rec
        log(f"[kbench] {case.name}: ref {ms_ref:.2f} ms, fused "
            f"{ms_fused:.2f} ms -> {rec['speedup']}x "
            f"({rec['lowering']}, parity={parity}, "
            f"interp_parity={interp_parity})")
    best = max(kernels, key=lambda n: kernels[n]["speedup"])
    return {
        "platform": platform,
        # same-backend ratios are honest anywhere, but only a TPU run is
        # a DEVICE claim — the standing no-CPU-numbers-as-device-numbers
        # rule; bench.py refuses to headline ms timings either way
        "device": on_device,
        "smoke": bool(smoke),
        "iters": iters,
        "parity_ok": all_parity,
        "kernels": kernels,
        "best_kernel": best,
        "best_speedup": kernels[best]["speedup"],
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }


def headline_keys(record: dict) -> dict:
    """The compact keys the bench headline carries (finalize() budget:
    dimensionless same-backend ratios + platform label, never raw ms)."""
    out = {
        "kbench_platform": record["platform"],
        "kbench_parity_ok": record["parity_ok"],
        "kbench_best": f"{record['best_kernel']}:"
                       f"{record['best_speedup']}x",
    }
    for name, rec in record["kernels"].items():
        out[f"kbench_{name}_speedup"] = rec["speedup"]
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pva-tpu-kbench",
        description="fused-kernel microbenchmarks vs XLA references at "
                    "real model shapes (docs/KERNELS.md)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes; harness/parity verification")
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--json", action="store_true")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    record = run_kbench(smoke=args.smoke, iters=args.iters)
    if args.json:
        print(json.dumps(record, indent=1))
    else:
        print(json.dumps(headline_keys(record)))
    if not record["parity_ok"]:
        _log("pva-tpu-kbench: PARITY VIOLATION — a fused kernel diverged "
             "from its XLA reference (record above); speed means nothing")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
