"""XLA reference chains for `pva-tpu-kbench` and the kernel parity tests.

Each reference is the UNFUSED op sequence the model graph runs with
`model.fused_kernels=off` (conv, then the resolved norm affine as its
own pass, then the activation) — the baseline every fused kernel in
ops/pallas_fused.py is timed and parity-checked against. They take the
same resolved (scale, bias) affine as the fused dispatchers so the two
sides compute the same function by construction, differing only in
lowering.

Kept out of kbench.py so tests import the references without pulling
the benchmark harness, and out of pallas_fused.py so the reference can
never accidentally share code with the thing it is checking.
"""

from __future__ import annotations

from jax import lax

from pytorchvideo_accelerate_tpu.ops.pallas_fused import apply_act


def ref_conv_bn_act(x, w, scale, bias, *, act: str):
    """Dense stride-1 SAME conv -> per-channel affine -> act."""
    y = lax.conv_general_dilated(
        x, w, (1, 1, 1), [(k // 2, k // 2) for k in w.shape[:3]],
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    return apply_act(y * scale + bias, act).astype(x.dtype)


def ref_pw_bn_act(x, w, scale, bias, *, act: str):
    """(1,1,1) conv -> affine -> act (the conv_a/conv_c chain)."""
    return ref_conv_bn_act(x, w, scale, bias, act=act)


def ref_dw_bn_act(x, k, scale, bias, *, act: str):
    """XLA grouped depthwise conv -> affine -> act (the conv_b chain)."""
    c = x.shape[-1]
    y = lax.conv_general_dilated(
        x, k, (1, 1, 1), [(d // 2, d // 2) for d in k.shape[:3]],
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        feature_group_count=c)
    return apply_act(y * scale + bias, act).astype(x.dtype)
