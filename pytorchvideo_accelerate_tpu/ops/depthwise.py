"""Depthwise 3D convolution implementations.

X3D is depthwise-conv-bound (every block's spatiotemporal conv_b is
depthwise, SURVEY §7 hard-part 2; BASELINE config 2), and MViT's pooling
convs are depthwise too. XLA:TPU lowers `feature_group_count=C` convs
through the grouped-convolution path, which tiles onto the MXU badly at
small per-group sizes (1 input channel per group = 1-deep matmuls). The
alternative here decomposes the depthwise conv into its taps: for a
k_t x k_h x k_w kernel, the output is a sum of k_t*k_h*k_w shifted,
per-channel-scaled copies of the input — pure VPU multiply-adds that XLA
fuses into one bandwidth-bound loop, no MXU involvement at all. For 3x3x3
that is 27 fused FMAs over the tensor: arithmetic intensity is low but so
is the op's share of FLOPs; what matters is not starving on a bad grouped
matmul schedule.

A third lowering, `pallas`, is the hand-tiled halo kernel
(ops/pallas_depthwise.py): one HBM->VMEM DMA per output tile (tile +
halo), all taps accumulated from the single VMEM-resident window — the
explicit-bandwidth answer where the shift decomposition's fused reads
may re-amplify. Stride-1 only (the non-entry blocks, which dominate);
strided calls under `pallas` fall back to the XLA grouped path.

Which implementation wins is an empirical, device-level question —
`scripts/perf_sweep.py` A/Bs them on real hardware. All impls create the
SAME parameter ("kernel", shape (kt, kh, kw, 1, C)) at the module's own
scope — exactly the tree `nn.Conv(feature_group_count=C, name=<same>)`
would create — so converted/pretrained checkpoints load identically and
the choice is a deployment knob (`--model.depthwise_impl
conv|shift|pallas`), not a model change.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp
from flax import linen as nn
from jax import lax

from pytorchvideo_accelerate_tpu.precision import f32_island


def depthwise_conv3d_shift(x, kernel, stride: Tuple[int, int, int] = (1, 1, 1),
                           padding: Tuple[int, int, int] = None):
    """Shift-and-accumulate depthwise conv.

    x: (B, T, H, W, C) NDHWC; kernel: (kt, kh, kw, 1, C) — the exact
    `nn.Conv(feature_group_count=C)` parameter layout. padding defaults to
    k//2 per dim (the package-wide conv padding convention, common.py).

    Accumulates in float32 regardless of input dtype (the grouped-conv MXU
    path accumulates in f32 too — chaining 26 bf16 adds would make the two
    lowerings diverge); the result is cast back to x.dtype.
    """
    kt, kh, kw, one, C = kernel.shape
    assert one == 1, f"expected depthwise kernel (kt,kh,kw,1,C), got {kernel.shape}"
    assert x.shape[-1] == C, (x.shape, kernel.shape)
    if padding is None:
        padding = (kt // 2, kh // 2, kw // 2)
    st, sh, sw = stride
    pt, ph, pw = padding
    xp = jnp.pad(x, ((0, 0), (pt, pt), (ph, ph), (pw, pw), (0, 0)))
    B = x.shape[0]
    T, H, W = x.shape[1:4]
    ot = (T + 2 * pt - kt) // st + 1
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1

    kernel32 = f32_island(kernel)
    out = None
    for it in range(kt):
        for ih in range(kh):
            for iw in range(kw):
                tap = lax.slice(
                    xp,
                    (0, it, ih, iw, 0),
                    (B, it + (ot - 1) * st + 1, ih + (oh - 1) * sh + 1,
                     iw + (ow - 1) * sw + 1, C),
                    (1, st, sh, sw, 1),
                )
                term = f32_island(tap) * kernel32[it, ih, iw, 0]
                out = term if out is None else out + term
    return out.astype(x.dtype)


class DepthwiseConv3D(nn.Module):
    """Depthwise conv3d with a selectable lowering, k//2 padding, no bias.

    Drop-in for `nn.Conv(C, kernel_size, strides, padding=[(k//2, k//2)...],
    feature_group_count=C, use_bias=False, name=<n>)`: the parameter is
    created at this module's own scope as "kernel" with the identical
    (kt, kh, kw, 1, C) shape, so the param path `<n>/kernel` — what the
    converter and existing checkpoints use — is unchanged by the swap.
    """

    features: int
    kernel_size: Tuple[int, int, int]
    stride: Tuple[int, int, int] = (1, 1, 1)
    impl: str = "conv"  # conv (XLA grouped) | shift (taps) | pallas (halo)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.impl not in ("conv", "shift", "pallas"):
            raise ValueError(
                f"depthwise impl must be conv|shift|pallas, got {self.impl!r}")
        kt, kh, kw = self.kernel_size
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (kt, kh, kw, 1, self.features),
            jnp.float32,
        )
        x = x.astype(self.dtype)
        kernel = kernel.astype(self.dtype)
        if self.impl == "shift":
            return depthwise_conv3d_shift(x, kernel, self.stride)
        if (self.impl == "pallas" and self.stride == (1, 1, 1)
                and all(k % 2 for k in self.kernel_size)):
            from pytorchvideo_accelerate_tpu.ops.pallas_depthwise import (
                pallas_depthwise3d_s1,
            )

            return pallas_depthwise3d_s1(x, kernel)
        # strided or even-kernel pallas calls fall through to the XLA
        # grouped path (the halo kernel hard-codes odd-kernel SAME
        # semantics; every in-tree consumer is odd, but an even kernel
        # must not silently change function)
        return lax.conv_general_dilated(
            x, kernel,
            window_strides=self.stride,
            padding=[(k // 2, k // 2) for k in self.kernel_size],
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            feature_group_count=self.features,
        )
