"""Hand-tiled Pallas depthwise conv3d — the halo-tile lowering.

Third lowering for the depthwise spatiotemporal convs (X3D conv_b/stem_t,
ir-CSN conv_b, MViT pooling; SURVEY §2.3-N3 "Pallas kernels where XLA conv
layouts underperform"). The existing options trade differently:

- XLA grouped conv: MXU path, but 1-channel groups tile the systolic
  array badly;
- shift decomposition (ops/depthwise.py): kt*kh*kw fused VPU FMAs, but
  XLA materializes strided windows per tap — up to 27x read amplification
  against HBM if the fusion re-reads.

This kernel makes the bandwidth bound explicit: the grid tiles the OUTPUT
over (batch, t-tiles, h-tiles); each program DMAs ONE overlapping input
window (the tile plus its (k-1)-halo, full W and C) from HBM into VMEM,
then accumulates all taps on the VPU in f32 from that single resident
copy — each input element crosses HBM->VMEM once per tile (plus halo
overlap ~ (tb+2)(hb+2)/(tb*hb) ≈ 1.56x at 8x8 tiles), and the output
tile is written once.

Honest bandwidth accounting: the wrapper pre-pads the input with
`jnp.pad` (pallas_call is opaque to XLA, so the padded tensor
materializes in HBM — one extra read+write of x per call, ~2x on top of
the kernel's own traffic). Net: ~3.5x input reads vs the shift path's
up-to-27x if XLA's tap fusion re-reads per tap — still the bandwidth
favorite on paper, but the pad copy is why this is an A/B candidate and
not a default. In-kernel clamped DMA windows would remove the copy at
the cost of per-tile boundary masking; do that if the sweep shows this
lowering winning but by less than the pad traffic. Whether any of it
beats XLA's schedule is a device question — `scripts/perf_sweep.py`
A/Bs all three lowerings.

Scope: stride 1 (the 22/26 X3D and 29/33 ir-CSN blocks; strided stage
entries fall back to the XLA grouped path in ops/depthwise.py). Training
works: a `jax.custom_vjp` reuses the SAME kernel for dx (correlation with
the tap-flipped kernel — the stride-1 transpose conv) and computes dk
with plain jnp strided reductions (27 elementwise dot products, cheap and
fusible; no kernel needed).

On non-TPU backends the kernel runs in interpreter mode so the identical
code path is unit-testable on the CPU harness (SURVEY §4), matching
ops/pallas_attention.py's convention.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from pytorchvideo_accelerate_tpu.precision import f32_island
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _dw_kernel(x_hbm, k_ref, o_ref, win_ref, sem, *,
               tb: int, hb: int, ow: int, kt: int, kh: int, kw: int):
    b = pl.program_id(0)
    ti = pl.program_id(1)
    hi = pl.program_id(2)
    # one DMA: the output tile's input window incl. halo (full W, full C)
    dma = pltpu.make_async_copy(
        x_hbm.at[b, pl.ds(ti * tb, tb + kt - 1),
                 pl.ds(hi * hb, hb + kh - 1)],
        win_ref, sem)
    dma.start()
    dma.wait()

    acc = jnp.zeros(o_ref.shape[1:], jnp.float32)  # (tb, hb, ow, C)
    for dt in range(kt):
        for dh in range(kh):
            for dw in range(kw):
                tap = win_ref[dt:dt + tb, dh:dh + hb, dw:dw + ow, :]
                acc += f32_island(tap) * f32_island(k_ref[
                    (dt * kh + dh) * kw + dw])
    o_ref[0] = acc.astype(o_ref.dtype)


def _dw_call(xp, kernel, dims, out_t: int, out_h: int, out_w: int,
             tb: int, hb: int, interpret: bool):
    """xp: pre-padded (B, Tp, Hp, Wp, C) with Tp >= n_t*tb + kt - 1 and
    Hp >= n_h*hb + kh - 1 (caller guarantees); kernel (kt*kh*kw, C)."""
    B, _, _, wp, c = xp.shape
    taps, _ = kernel.shape
    kt, kh, kw = dims
    n_t = -(-out_t // tb)
    n_h = -(-out_h // hb)
    return pl.pallas_call(
        functools.partial(_dw_kernel, tb=tb, hb=hb, ow=out_w,
                          kt=kt, kh=kh, kw=kw),
        out_shape=jax.ShapeDtypeStruct((B, out_t, out_h, out_w, c),
                                       xp.dtype),
        grid=(B, n_t, n_h),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((taps, c), lambda b, ti, hi: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tb, hb, out_w, c),
                               lambda b, ti, hi: (b, ti, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tb + kt - 1, hb + kh - 1, wp, c), xp.dtype),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(xp, kernel)


def _pad_for_tiles(x, kt, kh, kw, tb, hb):
    """SAME-pad plus tail padding so every (tb, hb) output tile's input
    window exists in the array."""
    b, t, h, w, c = x.shape
    n_t = -(-t // tb)
    n_h = -(-h // hb)
    pt, ph, pw = kt // 2, kh // 2, kw // 2
    return jnp.pad(x, (
        (0, 0),
        (pt, pt + (n_t * tb - t)),
        (ph, ph + (n_h * hb - h)),
        (pw, pw),
        (0, 0),
    ))


def _tile_sizes(t: int, h: int) -> tuple:
    return min(8, t), min(8, h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def pallas_depthwise3d_s1(x, kernel, interpret: Optional[bool] = None):
    """Depthwise conv3d, stride 1, SAME (k//2) padding, no bias.

    x: (B, T, H, W, C) NDHWC; kernel: (kt, kh, kw, 1, C) — the exact
    `nn.Conv(feature_group_count=C)` parameter layout (ops/depthwise.py).
    f32 accumulation, result cast to x.dtype (same contract as the other
    two lowerings)."""
    return _forward(x, kernel, interpret)


def _forward(x, kernel, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    kt, kh, kw, one, c = kernel.shape
    assert one == 1, f"expected (kt,kh,kw,1,C), got {kernel.shape}"
    b, t, h, w, _ = x.shape
    tb, hb = _tile_sizes(t, h)
    xp = _pad_for_tiles(x, kt, kh, kw, tb, hb)
    flat = f32_island(kernel.reshape(kt * kh * kw, c))
    return _dw_call(xp, flat, (kt, kh, kw), t, h, w, tb, hb, interpret)


def _fwd(x, kernel, interpret):
    return _forward(x, kernel, interpret), (x, kernel)


def _bwd(interpret, res, dy):
    x, kernel = res
    kt, kh, kw = kernel.shape[:3]
    # dx: correlation of dy with the tap-flipped kernel — the stride-1
    # depthwise transpose is the same stencil, so the same Pallas kernel
    # serves the backward data path
    flipped = kernel[::-1, ::-1, ::-1]
    dx = _forward(dy, flipped, interpret).astype(x.dtype)
    # dk: 27 strided elementwise dots — plain jnp, XLA fuses
    xp = jnp.pad(x, ((0, 0), (kt // 2, kt // 2), (kh // 2, kh // 2),
                     (kw // 2, kw // 2), (0, 0)))
    t, h, w = dy.shape[1:4]
    dy32 = f32_island(dy)
    rows = []
    for dt in range(kt):
        for dh in range(kh):
            for dw in range(kw):
                tap = xp[:, dt:dt + t, dh:dh + h, dw:dw + w, :]
                rows.append(jnp.sum(f32_island(tap) * dy32,
                                    axis=(0, 1, 2, 3)))
    dk = jnp.stack(rows).reshape(kt, kh, kw, 1, -1).astype(kernel.dtype)
    return dx, dk


pallas_depthwise3d_s1.defvjp(_fwd, _bwd)
