"""Compute ops: attention backends (dense XLA, Pallas flash, ring/Ulysses
context-parallel), depthwise-conv lowerings, and the fused conv/norm/act
kernel tier for the slowfast/x3d hot paths (docs/KERNELS.md;
`pva-tpu-kbench` microbenches each kernel against its XLA reference).

The fused kernels are NOT re-exported here on purpose: every in-tree
pallas import is lazy (function-local, the attention/depthwise
convention) so processes that never arm `fused_kernels` never pay the
pallas+mosaic import — reach them via
`pytorchvideo_accelerate_tpu.ops.pallas_fused`.
"""

from pytorchvideo_accelerate_tpu.ops.attention import dot_product_attention  # noqa: F401
