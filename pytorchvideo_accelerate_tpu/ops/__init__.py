"""Compute ops: attention backends (dense XLA, Pallas flash, ring/Ulysses
context-parallel) and custom kernels for the hot paths the model zoo shares.
"""

from pytorchvideo_accelerate_tpu.ops.attention import dot_product_attention  # noqa: F401
