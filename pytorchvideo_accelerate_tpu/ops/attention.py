"""Attention backends.

The transformer models (MViT, VideoMAE) call one entry point —
`dot_product_attention(q, k, v, backend=...)` — so the attention
implementation is a deployment choice, not a model choice:

- "dense": `jax.nn.dot_product_attention` (XLA fuses QK^T -> softmax -> AV;
  on TPU this hits the MXU with flash-style chunking from the compiler).
- "pallas": hand-tiled flash attention kernel (ops/pallas_attention.py) for
  sizes where XLA's default schedule underperforms.
- "ring": context-parallel ring attention over the mesh "context" axis
  (parallel/ring_attention.py) — sequence sharded, K/V blocks rotate over
  ICI via ppermute (SURVEY §5 long-context plan).

Shapes: q (B, Nq, H, D), k/v (B, Nkv, H, D) — BNHD, heads separate, the
layout XLA:TPU prefers for attention (no pre-transpose of the token axis).

Masked variants (`mask=`): a boolean mask broadcastable to
(B, H, Nq, Nk), True = attend. Used by the causal/windowed trunk
variants (models/videomae.py `attn_mask`) and the streaming KV-ring
incremental step (streaming/engine.py); the banded-time helpers below
build the masks from temporal-slot indices, so every caller shares one
definition of "slot qi may read slot kj iff 0 <= qi - kj < window".
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from pytorchvideo_accelerate_tpu.precision import f32_island


def dense_attention(q, k, v, scale: Optional[float] = None, kmask=None,
                    mask=None):
    """Reference attention. `kmask`: optional (Nk,) bool — False keys are
    excluded from the softmax (used for padded keys by the CP wrappers).
    `mask`: optional bool broadcastable to (B, H, Nq, Nk), True = attend
    (the banded-trunk contract)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # f32 softmax logits: the designed island every attention impl shares
    logits = f32_island(jnp.einsum("bqhd,bkhd->bhqk", q, k)) * scale
    if kmask is not None:
        logits = jnp.where(kmask[None, None, None, :], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def fused_attention(q, k, v, scale: Optional[float] = None, kmask=None,
                    mask=None):
    """XLA's fused attention (flash-style chunking on TPU — no materialized
    N^2 score matrix) with the same key-mask contract as `dense_attention`.
    The CP wrappers use this for their local attention so peak memory stays
    O(N) at the long sequences that motivate context parallelism."""
    if kmask is not None:
        km = kmask[None, None, None, :]
        mask = km if mask is None else jnp.logical_and(mask, km)
    return jax.nn.dot_product_attention(q, k, v, mask=mask, scale=scale)


def banded_time_mask(q_idx, k_idx, window: int):
    """Boolean band mask over ABSOLUTE temporal-slot indices: query slot
    qi may attend key slot kj iff ``0 <= qi - kj < window``.

    `q_idx` (..., Nq) / `k_idx` (..., Nk) int arrays (traced or static) ->
    (..., Nq, Nk) bool. Absolute indices are the wraparound-proof
    formulation the streaming KV rings rely on: a ring slot's position
    never aliases a future slot because the band is on the un-wrapped
    index, not the ring offset (docs/SERVING.md § trunk-reuse)."""
    delta = q_idx[..., :, None] - k_idx[..., None, :]
    return jnp.logical_and(delta >= 0, delta < window)


def temporal_band_mask(t: int, hw: int, window: int):
    """(t*hw, t*hw) bool mask for a full-clip trunk forward: token i at
    temporal slot i // hw attends token j iff its slot is within the
    trailing `window` slots (inclusive of its own). `window >= t` is plain
    temporal causality; smaller windows are the "windowed" variant. All
    hw spatial tokens of one slot share fate (space is never masked)."""
    slots = jnp.arange(t, dtype=jnp.int32)
    band = banded_time_mask(slots, slots, window)           # (t, t)
    return jnp.repeat(jnp.repeat(band, hw, axis=0), hw, axis=1)


def incremental_band_attention(q, k, v, q_slot, k_slot, window: int, hw: int,
                               impl: str = "fused"):
    """Incremental banded attention: the s-new-slots' queries against a
    cached-window + new K/V, masked by absolute temporal-slot index.

    q (B, nq*hw, H, D) — queries of the nq NEW slots only;
    k/v (B, nk*hw, H, D) — cached ring keys ++ new keys;
    q_slot (B, nq) / k_slot (B, nk) — absolute slot indices (traced).
    This is the exact attention op the streaming KV advance runs per
    layer, exposed standalone so pva-tpu-kbench can time it against the
    full-recompute attention at real model shapes."""
    band = banded_time_mask(q_slot, k_slot, window)          # (B, nq, nk)
    mask = jnp.repeat(jnp.repeat(band, hw, axis=1), hw, axis=2)
    fn = dense_attention if impl == "dense" else fused_attention
    return fn(q, k, v, mask=mask[:, None])                   # (B,1,Nq,Nk)


def dot_product_attention(q, k, v, backend: str = "dense",
                          axis_name: Optional[str] = None, mesh=None,
                          mask=None):
    """Route to an attention implementation.

    For the context-parallel backends ("ring"/"ulysses") exactly one of two
    calling conventions applies:
    - `mesh=...` — caller is ordinary auto-sharded (jit) code: the router
      opens a `shard_map` region over the mesh's context-parallel axis
      (``axis_name`` when also given, else resolved from the mesh layout —
      ``context`` on the library mesh, ``model`` on the 2-D train mesh)
      around just this attention call (composable with auto sharding
      everywhere else);
    - `axis_name=...` and no mesh — caller is already inside a `shard_map`
      with that axis bound; q/k/v are local sequence shards.

    `mask`: optional bool broadcastable to (B, H, Nq, Nk), True = attend
    (the causal/windowed trunk variants). Dense backend only: the pallas
    flash kernel and the context-parallel backends have no masked
    lowering here — they refuse loudly rather than silently dropping the
    mask (a bidirectional answer under a causal contract is a
    correctness bug, not a fallback).
    """
    if backend == "dense":
        # XLA's fused attention (flash-style chunking on TPU) — measured ~4x
        # faster than the materialized-einsum path at MViT token counts on
        # v5e; `dense_attention` above stays as the numerics reference.
        return jax.nn.dot_product_attention(q, k, v, mask=mask)
    if mask is not None:
        raise NotImplementedError(
            f"attention backend {backend!r} has no masked lowering; "
            "causal/windowed trunks need backend='dense' "
            "(model.attention) — see docs/SERVING.md § trunk-reuse")
    if backend == "pallas":
        from pytorchvideo_accelerate_tpu.ops.pallas_attention import flash_attention

        return flash_attention(q, k, v)
    if backend == "ring":
        from pytorchvideo_accelerate_tpu.parallel.ring_attention import (
            make_ring_attention, ring_attention,
        )

        if mesh is not None:
            return make_ring_attention(mesh, axis_name)(q, k, v)
        if axis_name is None:
            raise ValueError("ring attention needs a mesh or the context-axis name")
        return ring_attention(q, k, v, axis_name=axis_name)
    if backend == "ulysses":
        from pytorchvideo_accelerate_tpu.parallel.ulysses import (
            make_ulysses_attention, ulysses_attention,
        )

        if mesh is not None:
            return make_ulysses_attention(mesh, axis_name)(q, k, v)
        if axis_name is None:
            raise ValueError("ulysses attention needs a mesh or the context-axis name")
        return ulysses_attention(q, k, v, axis_name=axis_name)
    raise ValueError(f"unknown attention backend {backend!r}")
