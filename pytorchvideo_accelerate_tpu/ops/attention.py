"""Attention backends.

The transformer models (MViT, VideoMAE) call one entry point —
`dot_product_attention(q, k, v, backend=...)` — so the attention
implementation is a deployment choice, not a model choice:

- "dense": `jax.nn.dot_product_attention` (XLA fuses QK^T -> softmax -> AV;
  on TPU this hits the MXU with flash-style chunking from the compiler).
- "pallas": hand-tiled flash attention kernel (ops/pallas_attention.py) for
  sizes where XLA's default schedule underperforms.
- "ring": context-parallel ring attention over the mesh "context" axis
  (parallel/ring_attention.py) — sequence sharded, K/V blocks rotate over
  ICI via ppermute (SURVEY §5 long-context plan).

Shapes: q (B, Nq, H, D), k/v (B, Nkv, H, D) — BNHD, heads separate, the
layout XLA:TPU prefers for attention (no pre-transpose of the token axis).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from pytorchvideo_accelerate_tpu.precision import f32_island


def dense_attention(q, k, v, scale: Optional[float] = None, kmask=None):
    """Reference attention. `kmask`: optional (Nk,) bool — False keys are
    excluded from the softmax (used for padded keys by the CP wrappers)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # f32 softmax logits: the designed island every attention impl shares
    logits = f32_island(jnp.einsum("bqhd,bkhd->bhqk", q, k)) * scale
    if kmask is not None:
        logits = jnp.where(kmask[None, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def fused_attention(q, k, v, scale: Optional[float] = None, kmask=None):
    """XLA's fused attention (flash-style chunking on TPU — no materialized
    N^2 score matrix) with the same key-mask contract as `dense_attention`.
    The CP wrappers use this for their local attention so peak memory stays
    O(N) at the long sequences that motivate context parallelism."""
    mask = None if kmask is None else kmask[None, None, None, :]
    return jax.nn.dot_product_attention(q, k, v, mask=mask, scale=scale)


def dot_product_attention(q, k, v, backend: str = "dense",
                          axis_name: Optional[str] = None, mesh=None):
    """Route to an attention implementation.

    For the context-parallel backends ("ring"/"ulysses") exactly one of two
    calling conventions applies:
    - `mesh=...` — caller is ordinary auto-sharded (jit) code: the router
      opens a `shard_map` region over the mesh's context-parallel axis
      (``axis_name`` when also given, else resolved from the mesh layout —
      ``context`` on the library mesh, ``model`` on the 2-D train mesh)
      around just this attention call (composable with auto sharding
      everywhere else);
    - `axis_name=...` and no mesh — caller is already inside a `shard_map`
      with that axis bound; q/k/v are local sequence shards.
    """
    if backend == "dense":
        # XLA's fused attention (flash-style chunking on TPU) — measured ~4x
        # faster than the materialized-einsum path at MViT token counts on
        # v5e; `dense_attention` above stays as the numerics reference.
        return jax.nn.dot_product_attention(q, k, v)
    if backend == "pallas":
        from pytorchvideo_accelerate_tpu.ops.pallas_attention import flash_attention

        return flash_attention(q, k, v)
    if backend == "ring":
        from pytorchvideo_accelerate_tpu.parallel.ring_attention import (
            make_ring_attention, ring_attention,
        )

        if mesh is not None:
            return make_ring_attention(mesh, axis_name)(q, k, v)
        if axis_name is None:
            raise ValueError("ring attention needs a mesh or the context-axis name")
        return ring_attention(q, k, v, axis_name=axis_name)
    if backend == "ulysses":
        from pytorchvideo_accelerate_tpu.parallel.ulysses import (
            make_ulysses_attention, ulysses_attention,
        )

        if mesh is not None:
            return make_ulysses_attention(mesh, axis_name)(q, k, v)
        if axis_name is None:
            raise ValueError("ulysses attention needs a mesh or the context-axis name")
        return ulysses_attention(q, k, v, axis_name=axis_name)
    raise ValueError(f"unknown attention backend {backend!r}")
