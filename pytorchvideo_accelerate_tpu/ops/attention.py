"""Attention backends.

The transformer models (MViT, VideoMAE) call one entry point —
`dot_product_attention(q, k, v, backend=...)` — so the attention
implementation is a deployment choice, not a model choice:

- "dense": `jax.nn.dot_product_attention` (XLA fuses QK^T -> softmax -> AV;
  on TPU this hits the MXU with flash-style chunking from the compiler).
- "pallas": hand-tiled flash attention kernel (ops/pallas_attention.py) for
  sizes where XLA's default schedule underperforms.
- "ring": context-parallel ring attention over the mesh "context" axis
  (parallel/ring_attention.py) — sequence sharded, K/V blocks rotate over
  ICI via ppermute (SURVEY §5 long-context plan).

Shapes: q (B, Nq, H, D), k/v (B, Nkv, H, D) — BNHD, heads separate, the
layout XLA:TPU prefers for attention (no pre-transpose of the token axis).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def dense_attention(q, k, v, scale: Optional[float] = None):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def dot_product_attention(q, k, v, backend: str = "dense", axis_name: Optional[str] = None):
    """Route to an attention implementation. `axis_name` is required for the
    ring backend (the mesh axis the sequence is sharded over)."""
    if backend == "dense":
        return dense_attention(q, k, v)
    if backend == "pallas":
        from pytorchvideo_accelerate_tpu.ops.pallas_attention import flash_attention

        return flash_attention(q, k, v)
    if backend == "ring":
        from pytorchvideo_accelerate_tpu.parallel.ring_attention import ring_attention

        if axis_name is None:
            raise ValueError("ring attention needs the context-axis name")
        return ring_attention(q, k, v, axis_name=axis_name)
    raise ValueError(f"unknown attention backend {backend!r}")
