"""ctypes bindings for the native loader runtime (native/pva_native.cpp).

The shared library is built on first use with the system g++ (no external
deps, ~1s) and cached next to the source; environments without a toolchain
get `load() -> None` and the pure-Python loader paths keep working.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock

logger = get_logger("pva_tpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "pva_native.cpp")
_LIB_DIR = os.environ.get(
    "PVA_NATIVE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "pva_tpu"),
)
_LIB = os.path.join(_LIB_DIR, "libpva_native.so")

_lock = make_lock("native._lock")
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    os.makedirs(_LIB_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
           _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native build failed (%s); using pure-Python loader", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None if unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            if not os.path.exists(_SRC) and os.path.exists(_LIB):
                pass  # installed without sources: use the cached build
            elif not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            logger.warning("native load failed (%s); using pure-Python loader", e)
            _load_failed = True
            return None

        u64, u32, i32 = ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int
        p = ctypes.c_void_p
        lib.pva_rb_total_size.restype = u64
        lib.pva_rb_total_size.argtypes = [u32, u64]
        lib.pva_rb_init.restype = i32
        lib.pva_rb_init.argtypes = [p, u32, u64]
        lib.pva_rb_slot_ptr.restype = p
        lib.pva_rb_slot_ptr.argtypes = [p, u32]
        lib.pva_rb_slot_bytes.restype = u64
        lib.pva_rb_slot_bytes.argtypes = [p]
        lib.pva_rb_acquire.restype = i32
        lib.pva_rb_acquire.argtypes = [p, i32]
        lib.pva_rb_commit.restype = i32
        lib.pva_rb_commit.argtypes = [p, u32, u64, u64]
        lib.pva_rb_pop.restype = i32
        lib.pva_rb_pop.argtypes = [p, i32, ctypes.POINTER(u64), ctypes.POINTER(u64)]
        lib.pva_rb_release.restype = i32
        lib.pva_rb_release.argtypes = [p, u32]
        lib.pva_rb_shutdown.restype = None
        lib.pva_rb_shutdown.argtypes = [p]
        lib.pva_rb_ready_count.restype = u32
        lib.pva_rb_ready_count.argtypes = [p]
        lib.pva_gather_copy.restype = i32
        lib.pva_gather_copy.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(u64), ctypes.POINTER(u64), u32, u32,
        ]
        _lib = lib
        return _lib


from pytorchvideo_accelerate_tpu.native.ringbuf import (  # noqa: E402,F401
    ShmRing,
    gather_copy,
)
from pytorchvideo_accelerate_tpu.native.shm_loader import (  # noqa: E402,F401
    ShmWorkerPool,
)
