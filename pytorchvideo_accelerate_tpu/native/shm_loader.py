"""Process-based clip workers over native shm rings (SURVEY §2.3-N8).

The thread-pool loader (data/pipeline.py) is enough while cv2 decode
releases the GIL, but the numpy transform stack serializes on it; the
reference's answer is worker *processes* (torch DataLoader), paying pickle +
pipe per sample. This pool forks workers that write decoded/transformed
samples straight into shared memory:

- ONE RING PER WORKER, created fresh per epoch: worker w produces epoch
  positions w, w+W, ... in order into its own ring, and the consumer pops
  position p from ring p%W — samples arrive in order by construction, so
  there is no reordering stash, memory is bounded by the ring sizes, and a
  slow worker back-pressures only itself;
- samples cross as zero-copy views and are copied exactly once into the
  batch buffer (native `gather_copy`, no GIL);
- a worker exception is delivered as an in-band "__error__" sample, so the
  consumer raises the real message immediately (parity with the thread
  path's fut.result()) instead of timing out;
- fork() per epoch, copy-on-write. KNOWN LIMITATION (shared with torch's
  fork-mode DataLoader): forking a heavily threaded parent can deadlock a
  child on an inherited lock; children therefore run only numpy/cv2/ring
  code (no logging, no JAX) between fork and os._exit. `transport="thread"`
  remains the default; select "process" when decode is the bottleneck.
"""

from __future__ import annotations

import os
import signal
import traceback
from typing import Dict, Iterator, List, Sequence, Tuple

import numpy as np

from pytorchvideo_accelerate_tpu.native.ringbuf import (
    ShmRing,
    pack_sample,
    sample_nbytes,
    unpack_sample,
)

ERROR_KEY = "__error__"


class ShmWorkerPool:
    """Decode workers in forked processes, samples through per-worker rings."""

    def __init__(self, source, num_workers: int = 4, slots_per_worker: int = 0,
                 slot_bytes: int = 0, probe_epoch: int = 0,
                 timeout_ms: int = 60_000):
        self.source = source
        self.num_workers = max(1, num_workers)
        self.timeout_ms = timeout_ms
        if not slot_bytes:
            probe = source.get(0, probe_epoch)
            # headroom for per-sample shape jitter + header
            slot_bytes = int(sample_nbytes(probe) * 1.25) + 1024
        self.slot_bytes = slot_bytes
        self.slots_per_worker = slots_per_worker or 4
        self._rings: List[ShmRing] = []
        self._pids: List[int] = []

    # --- worker body ------------------------------------------------------

    def _worker(self, wid: int, indices: np.ndarray, epoch: int) -> None:
        ring = self._rings[wid]
        try:
            for pos in range(wid, len(indices), self.num_workers):
                sample = self.source.get(int(indices[pos]), epoch)
                slot = ring.acquire(self.timeout_ms)
                if slot < 0:  # shutdown or stuck consumer
                    return
                n = pack_sample(sample, ring.slot_view(slot))
                ring.commit(slot, n, tag=pos)
        except BaseException:
            # in-band error delivery; consumer raises with this traceback
            msg = traceback.format_exc().encode()[-4096:]
            slot = ring.acquire(2000)
            if slot >= 0:
                err = {ERROR_KEY: np.frombuffer(msg, np.uint8)}
                n = pack_sample(err, ring.slot_view(slot))
                ring.commit(slot, n, tag=0)
        finally:
            os._exit(0)

    def _spawn(self, indices: np.ndarray, epoch: int) -> None:
        self._rings = [ShmRing(self.slots_per_worker, self.slot_bytes)
                       for _ in range(self.num_workers)]
        self._pids = []
        for w in range(self.num_workers):
            pid = os.fork()
            if pid == 0:
                self._worker(w, indices, epoch)  # never returns
            self._pids.append(pid)

    def _teardown(self) -> None:
        for ring in self._rings:
            ring.shutdown()  # wakes any worker blocked in acquire()
        for pid in self._pids:
            try:
                os.waitpid(pid, 0)
            except ChildProcessError:
                pass
        self._pids = []
        self._rings = []

    # --- consumer ---------------------------------------------------------

    def map_epoch(self, indices: Sequence[int], epoch: int,
                  start: int = 0) -> Iterator[Tuple[Dict[str, np.ndarray], "callable"]]:
        """Yield (sample, done) for positions start..len(indices)-1 IN ORDER.

        `sample` holds zero-copy views into a ring slot; call `done()` after
        copying it out (releases the slot). Rings + workers live for this
        call only; early generator exit tears them down promptly.
        """
        indices = np.asarray(indices[start:])
        self._spawn(indices, epoch)
        dead: Dict[int, int] = {}  # wid -> waitpid status
        try:
            for pos in range(len(indices)):
                wid = pos % self.num_workers
                ring = self._rings[wid]
                # short-interval pops with a liveness check between them:
                # a SIGKILLed worker is reported within ~1s (with its wait
                # status) instead of burning the full consumer timeout
                deadline_ms = self.timeout_ms
                slot = -1
                while deadline_ms > 0:
                    step_ms = min(deadline_ms, 1000)
                    slot, nbytes, tag = ring.pop(step_ms)
                    if slot >= 0:
                        break
                    deadline_ms -= step_ms
                    if wid not in dead:
                        pid_done, status = os.waitpid(self._pids[wid],
                                                      os.WNOHANG)
                        if pid_done:
                            dead[wid] = status
                    if wid in dead:
                        raise RuntimeError(
                            f"shm pool: worker {wid} (pid {self._pids[wid]}) "
                            f"died (wait status {dead[wid]}) before producing "
                            f"position {pos}"
                        )
                if slot < 0:
                    raise TimeoutError(
                        f"shm pool: no sample for position {pos} from worker "
                        f"{wid} (status {slot})"
                    )
                sample = unpack_sample(ring.slot_view(slot)[:nbytes])
                if ERROR_KEY in sample:
                    raise RuntimeError(
                        "shm worker failed:\n"
                        + bytes(sample[ERROR_KEY]).decode(errors="replace")
                    )
                if tag != pos:  # protocol violation — should be impossible
                    raise RuntimeError(f"shm pool: expected pos {pos}, got {tag}")
                yield sample, (lambda r=ring, s=slot: r.release(s))
        finally:
            self._teardown()

    def close(self) -> None:
        for pid in self._pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        self._teardown()
