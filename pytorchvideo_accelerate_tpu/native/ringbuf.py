"""Python view of the native shm ring buffer + sample (de)serialization.

A slot carries one sample dict of fixed-shape numpy arrays with a tiny
binary header (key table + dtype/shape), so workers in *other processes*
write decoded clips straight into shared pages — no pickling, no pipes
(the torch-DataLoader transport this replaces, SURVEY §2.3-N8).
"""

from __future__ import annotations

import ctypes
import mmap
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import pytorchvideo_accelerate_tpu.native as native

_DTYPES = [np.dtype(np.float32), np.dtype(np.int32), np.dtype(np.uint8),
           np.dtype(np.float16), np.dtype(np.int64), np.dtype(np.bool_)]
try:  # bf16 clips (data/transforms.py output_dtype="bfloat16")
    import ml_dtypes

    _DTYPES.append(np.dtype(ml_dtypes.bfloat16))
except Exception:  # pragma: no cover - ml_dtypes ships with jax
    pass
_DTYPE_CODE = {d: i for i, d in enumerate(_DTYPES)}


def pack_sample(sample: Dict[str, np.ndarray], buf: memoryview) -> int:
    """Serialize a sample dict into `buf`; returns bytes written.

    Layout: u32 n_arrays, then per array: u16 keylen, key bytes, u8 dtype
    code, u8 ndim, u32 shape[ndim], u64 nbytes, payload (8-byte aligned).
    """
    off = 4
    n = 0
    for key, arr in sample.items():
        # NB: np.asarray, not ascontiguousarray — the latter promotes 0-d
        # scalars to (1,); tobytes() below handles layout regardless
        arr = np.asarray(arr)
        kb = key.encode()
        struct.pack_into(f"<H{len(kb)}sBB", buf, off, len(kb), kb,
                         _DTYPE_CODE[arr.dtype], arr.ndim)
        off += 2 + len(kb) + 2
        struct.pack_into(f"<{arr.ndim}I", buf, off, *arr.shape)
        off += 4 * arr.ndim
        nbytes = arr.nbytes
        struct.pack_into("<Q", buf, off, nbytes)
        off += 8
        off = (off + 7) & ~7
        buf[off:off + nbytes] = arr.tobytes()  # single copy into shm
        off += nbytes
        n += 1
    struct.pack_into("<I", buf, 0, n)
    return off


def unpack_sample(buf: memoryview, copy: bool = False) -> Dict[str, np.ndarray]:
    """Deserialize; by default returns zero-copy views into the slot (valid
    until the slot is released — callers batch-copy before releasing)."""
    (n,) = struct.unpack_from("<I", buf, 0)
    off = 4
    out = {}
    for _ in range(n):
        (klen,) = struct.unpack_from("<H", buf, off)
        key = bytes(buf[off + 2:off + 2 + klen]).decode()
        code, ndim = struct.unpack_from("<BB", buf, off + 2 + klen)
        off += 2 + klen + 2
        shape = struct.unpack_from(f"<{ndim}I", buf, off)
        off += 4 * ndim
        (nbytes,) = struct.unpack_from("<Q", buf, off)
        off += 8
        off = (off + 7) & ~7
        arr = np.frombuffer(buf, dtype=_DTYPES[code], count=int(
            nbytes // _DTYPES[code].itemsize), offset=off).reshape(shape)
        out[key] = arr.copy() if copy else arr
        off += nbytes
    return out


def sample_nbytes(sample: Dict[str, np.ndarray]) -> int:
    total = 4
    for key, arr in sample.items():
        total += 2 + len(key.encode()) + 2 + 4 * np.ndim(arr) + 8 + 8
        total += np.asarray(arr).nbytes
    return total


class ShmRing:
    """A native ring buffer in an anonymous shared mmap (inherited by forked
    worker processes). Parent creates it pre-fork; children reuse `ring.buf`."""

    def __init__(self, n_slots: int, slot_bytes: int):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self.lib = lib
        self.n_slots = n_slots
        total = lib.pva_rb_total_size(n_slots, slot_bytes)
        self.mm = mmap.mmap(-1, total)  # MAP_SHARED | MAP_ANONYMOUS
        self._base = ctypes.addressof(ctypes.c_char.from_buffer(self.mm))
        if lib.pva_rb_init(self._base, n_slots, slot_bytes) != 0:
            raise RuntimeError("pva_rb_init failed")
        self.slot_bytes = lib.pva_rb_slot_bytes(self._base)

    # --- producer side ----------------------------------------------------
    def acquire(self, timeout_ms: int = 10_000) -> int:
        return self.lib.pva_rb_acquire(self._base, timeout_ms)

    def commit(self, slot: int, nbytes: int, tag: int) -> None:
        self.lib.pva_rb_commit(self._base, slot, nbytes, tag)

    def put_sample(self, sample: Dict[str, np.ndarray], tag: int,
                   timeout_ms: int = 10_000) -> bool:
        slot = self.acquire(timeout_ms)
        if slot < 0:
            return False
        n = pack_sample(sample, self.slot_view(slot))
        self.commit(slot, n, tag)
        return True

    # --- consumer side ----------------------------------------------------
    def pop(self, timeout_ms: int = 10_000) -> Tuple[int, int, int]:
        nbytes = ctypes.c_uint64()
        tag = ctypes.c_uint64()
        slot = self.lib.pva_rb_pop(self._base, timeout_ms,
                                   ctypes.byref(nbytes), ctypes.byref(tag))
        return slot, nbytes.value, tag.value

    def release(self, slot: int) -> None:
        self.lib.pva_rb_release(self._base, slot)

    def slot_view(self, slot: int) -> memoryview:
        ptr = self.lib.pva_rb_slot_ptr(self._base, slot)
        off = ptr - self._base
        return memoryview(self.mm)[off:off + self.slot_bytes]

    def ready_count(self) -> int:
        return self.lib.pva_rb_ready_count(self._base)

    def shutdown(self) -> None:
        self.lib.pva_rb_shutdown(self._base)

    def close(self) -> None:
        self.shutdown()
        # mm stays mapped until gc so outstanding views stay valid


def gather_copy(dst: np.ndarray, parts: Sequence[np.ndarray],
                offsets: Optional[Sequence[int]] = None,
                n_threads: int = 4) -> None:
    """dst.flat bytes[off_i:] = parts[i] — multithreaded memcpy without the
    GIL (batch assembly; replaces np.stack's serial copies)."""
    lib = native.load()
    n = len(parts)
    if offsets is None:
        offsets, acc = [], 0
        for part in parts:
            offsets.append(acc)
            acc += part.nbytes
    if lib is None:  # pure-python fallback
        view = dst.reshape(-1).view(np.uint8)
        for off, part in zip(offsets, parts):
            pb = np.ascontiguousarray(part).reshape(-1).view(np.uint8)
            view[off:off + part.nbytes] = pb
        return
    srcs = (ctypes.c_char_p * n)()
    offs = (ctypes.c_uint64 * n)(*offsets)
    sizes = (ctypes.c_uint64 * n)()
    keepalive: List[np.ndarray] = []
    for i, part in enumerate(parts):
        part = np.ascontiguousarray(part)
        keepalive.append(part)
        srcs[i] = ctypes.cast(part.ctypes.data, ctypes.c_char_p)
        sizes[i] = part.nbytes
    lib.pva_gather_copy(
        ctypes.cast(dst.ctypes.data, ctypes.c_char_p), srcs, offs, sizes,
        n, n_threads,
    )
