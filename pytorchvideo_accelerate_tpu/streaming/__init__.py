"""pva-tpu-stream: incremental streaming inference (docs/SERVING.md §
streaming).

A *session* owns a device-resident rolling window ring inside an
`InferenceEngine`'s mesh; each advance ships only the new frames host->
device, updates the ring in place through a jitted donated update, and
re-scores the cached window — so monitoring a live stream at stride *s*
stops paying the ``T/s``x redundant decode / H2D / patch-embed tax the
one-shot clip-classification path charges per emitted label.

Layers:
- `streaming/session.py`  — the session table: ids, ring-slot leases,
  TTL + HBM-budget admission (`SessionTable`);
- `streaming/engine.py`   — `StreamingEngine`: ring pools, the compiled
  (bucket, stride, geometry) advance/establish functions, hot-swap state
  carry (`carry_state_from`), and the full-recompute parity reference.

The fleet integration (affinity routing, scheduler session launches,
`/stream`, the stream load generator) lives where the fleet lives:
fleet/router.py, fleet/scheduler.py, serving/server.py, fleet/loadgen.py.
"""

from pytorchvideo_accelerate_tpu.streaming.engine import (  # noqa: F401
    StreamingEngine,
)
from pytorchvideo_accelerate_tpu.streaming.session import (  # noqa: F401
    SessionAdmissionError,
    SessionError,
    SessionTable,
    SessionUnknownError,
)
