"""Session table: ids -> ring-slot leases, under a TTL + HBM budget.

A streaming session is device state (its rolling window ring occupies a
slot of a pre-allocated ring pool, streaming/engine.py), so admission is a
MEMORY decision, not a queue decision: the table refuses a new session
when every slot of its geometry's pool is held by a *live* session
(`SessionAdmissionError`, a `QueueFullError` — the HTTP front answers the
standard ``503 + Retry-After``), and reclaims slots from sessions idle
past ``ttl_s`` (a stream that stopped advancing is a leak, not a client).

The table is pure host bookkeeping — sid -> (pool key, slot, write
offset, stride) — and deliberately knows nothing about jax: the engine
owns the device arrays and calls in here under the table's own lock.
Thread-safety: the scheduler's flush thread advances sessions while the
HTTP front establishes/ends them and a hot-swap carries the whole table
to a green engine; every mutation runs under `_lock`
(`@shared_state`-registered, pva-tpu-tsan covers the churn).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from pytorchvideo_accelerate_tpu.serving.batcher import QueueFullError
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

logger = get_logger("pva_tpu")


class SessionError(ValueError):
    """Malformed streaming request (geometry/stride mismatch) -> 400."""


class SessionUnknownError(SessionError):
    """Advance for a session this replica does not hold and no resendable
    window to re-establish from -> the client must resend its window
    (fleet routing re-establishes transparently when the window rides
    along, which is how replica death stays client-invisible)."""


class SessionAdmissionError(QueueFullError):
    """No free ring slot and no TTL-expired session to evict: the HBM
    session budget is genuinely exhausted -> 503 + Retry-After."""


@dataclass
class SessionState:
    """Host-side record of one device-resident session."""

    sid: str
    pool_key: tuple     # ring geometry key (engine-owned vocabulary)
    slot: int           # row of the geometry's ring pool
    stride: int         # frames per advance, fixed at establish
    window: int         # ring length T (frames)
    off: int = 0        # next write offset (multiple of stride; oldest frame)
    frames_seen: int = 0
    last_active: float = field(default_factory=time.monotonic)


@shared_state("_sessions", "_free")
class SessionTable:
    """sid -> `SessionState`, slot free-lists per ring pool, TTL+budget
    admission. The engine registers each pool's capacity once
    (`register_pool`) and then leases/frees slots through here."""

    def __init__(self, *, ttl_s: float = 120.0, retry_after_s: float = 1.0,
                 registry=None, name: str = "stream"):
        from pytorchvideo_accelerate_tpu import obs

        self.ttl_s = float(ttl_s)
        self.retry_after_s = float(retry_after_s)
        self.name = name
        self._lock = make_lock("SessionTable._lock")
        self._sessions: Dict[str, SessionState] = {}
        self._free: Dict[tuple, List[int]] = {}
        reg = registry if registry is not None else obs.get_registry()
        self._g_live = reg.gauge(
            "pva_stream_sessions", "live streaming sessions, by table",
            labelnames=("table",))
        self._g_live.set_function(lambda: float(len(self._sessions)),
                                  table=name)
        self._c_evicted = reg.counter(
            "pva_stream_evicted_total",
            "sessions reclaimed by TTL eviction, by table",
            labelnames=("table",))

    # --- pools ------------------------------------------------------------

    def register_pool(self, pool_key: tuple, capacity: int) -> None:
        """Declare a ring pool of `capacity` leasable slots (idempotent)."""
        with self._lock:
            if pool_key not in self._free:
                self._free[pool_key] = list(range(int(capacity)))

    def pool_capacity(self, pool_key: tuple) -> int:
        with self._lock:
            free = len(self._free.get(pool_key, ()))
        return free + sum(1 for s in self.sessions()
                          if s.pool_key == pool_key)

    # --- lifecycle --------------------------------------------------------

    def establish(self, sid: str, pool_key: tuple, *, stride: int,
                  window: int) -> SessionState:
        """Lease a slot for `sid` (replacing any prior incarnation of the
        same id — a client re-establish after replica death or hot-swap is
        the SAME stream, not a second one). Evicts the least-recently
        active TTL-expired session of the pool when no slot is free;
        raises `SessionAdmissionError` when every holder is live."""
        now = time.monotonic()
        with self._lock:
            prior = self._sessions.pop(sid, None)
            if prior is not None and prior.pool_key == pool_key:
                slot = prior.slot  # same geometry: reuse the lease
            else:
                if prior is not None:  # geometry changed: free the old lease
                    self._free[prior.pool_key].append(prior.slot)
                slot = self._lease_locked(pool_key, now)
            state = SessionState(sid=sid, pool_key=pool_key, slot=slot,
                                 stride=int(stride), window=int(window),
                                 last_active=now)
            self._sessions[sid] = state
            return state

    def _lease_locked(self, pool_key: tuple, now: float) -> int:
        """Caller holds `_lock` (establish's `with` block): pop a free
        slot, or reclaim the stalest TTL-expired session's slot, or
        refuse admission."""
        free = self._free.get(pool_key)
        if free is None:
            raise SessionError(f"no ring pool registered for {pool_key}")
        if free:
            return free.pop()
        # budget full: reclaim the stalest EXPIRED session (never a live
        # one — a session mid-advance must not lose its ring under itself)
        victim = None
        for s in self._sessions.values():
            if s.pool_key != pool_key:
                continue
            if now - s.last_active < self.ttl_s:
                continue
            if victim is None or s.last_active < victim.last_active:
                victim = s
        if victim is None:
            raise SessionAdmissionError(
                f"session budget exhausted ({self.name}: every ring slot "
                "held by a live session); retry later",
                retry_after_s=self.retry_after_s)
        del self._sessions[victim.sid]  # pva: disable=lock-discipline -- _lease_locked is called only from establish's `with self._lock` block (caller-holds-lock contract in the docstring)
        self._c_evicted.inc(table=self.name)
        logger.info("stream: evicted idle session %s (%.1fs > ttl %.1fs)",
                    victim.sid, now - victim.last_active, self.ttl_s)
        return victim.slot

    def get(self, sid: str) -> Optional[SessionState]:
        with self._lock:
            return self._sessions.get(sid)

    def advanced(self, sid: str, frames: int) -> None:
        """Commit one successful advance: rotate the write offset and
        refresh the TTL clock. Called by the engine AFTER the device
        update lands, so a failed launch never moves the window."""
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                return
            s.off = (s.off + frames) % s.window
            s.frames_seen += frames
            s.last_active = time.monotonic()

    def end(self, sid: str) -> bool:
        """Client-initiated close: free the slot now (no TTL wait)."""
        with self._lock:
            s = self._sessions.pop(sid, None)
            if s is None:
                return False
            self._free[s.pool_key].append(s.slot)
            return True

    def sweep(self) -> int:
        """Reclaim every TTL-expired session; returns the count. Called
        from the advance path (no dedicated poller thread to leak)."""
        now = time.monotonic()
        evicted = 0
        with self._lock:
            for sid in [sid for sid, s in self._sessions.items()
                        if now - s.last_active >= self.ttl_s]:
                s = self._sessions.pop(sid)
                self._free[s.pool_key].append(s.slot)
                self._c_evicted.inc(table=self.name)
                evicted += 1
        if evicted:
            logger.info("stream: TTL sweep reclaimed %d session(s)", evicted)
        return evicted

    def sessions(self) -> List[SessionState]:
        with self._lock:
            return list(self._sessions.values())

    def adopt(self, other: "SessionTable") -> None:
        """Hot-swap state carry: take over `other`'s sessions and slot
        free-lists wholesale (the green engine adopts the blue table's
        leases — ring POOLS move separately, engine.carry_state_from).
        Lock order: self then other, constant across callers."""
        with self._lock:
            with other._lock:
                self._sessions = dict(other._sessions)
                self._free = {k: list(v) for k, v in other._free.items()}

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            live = len(self._sessions)
            free = sum(len(v) for v in self._free.values())
        return {"sessions_live": float(live), "slots_free": float(free),
                "evicted": self._c_evicted.value(table=self.name)}
