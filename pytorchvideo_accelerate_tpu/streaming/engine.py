"""StreamingEngine: device-resident rolling-window rings + incremental
advance steps, compiled once per (bucket, stride, geometry).

The recompute this eliminates (docs/SERVING.md § streaming): one-shot
clip classification re-ships and re-embeds the whole ``(T, H, W, C)``
window per emitted label, so a live stream scored at stride *s* pays
``T/s``x redundant H2D and patch-embed work. Here a session's window
lives ON DEVICE in a slot of a pre-allocated ring pool; an advance ships
only the *s* new frames, writes them into the ring in place (jitted,
pool donated — graphcheck-style zero double-buffering), and re-scores
the cached window.

Ring families, chosen by the served model:

- **frame ring** (conv families — tiny3d/x3d/resnet/csn/r2plus1d/c2d):
  the ring holds raw frames in the engine's input dtype; the advance
  saves H2D + host staging and the full trunk re-runs over the cached
  window (3-D convs mix time globally — no exact partial re-use seam).
- **token ring** (`VideoMAEClassifier`): the cube embedding is a VALID
  conv with kernel == stride, so each tubelet's token depends only on
  its own pixels — the ring caches PRE-positional patch tokens per
  temporal slot, the advance embeds just the new frames, and the trunk
  runs over cached tokens (positional embeddings are added at trunk
  time in window order, so the rotating ring start is invisible to the
  model). A raw-frame ring is kept alongside as the weight-independent
  carry substrate: across a blue/green hot-swap the green engine
  re-embeds every live ring from raw frames under ITS weights at
  cutover (`carry_state_from`, compiled in advance by
  `prepare_carry_from`), so cached tokens can never go stale against
  swapped weights.
- **KV rings** (`VideoMAEClassifier` + ``trunk="causal"|"windowed"``,
  docs/SERVING.md § trunk-reuse): beyond the embed, the TRUNK itself is
  reused. The served backbone runs a banded temporal attention mask
  (0 <= q_slot - k_slot < W; models/videomae.py `attn_mask`), under
  which each slot's per-layer K/V and final hidden state is a pure
  function of its trailing window — so they are cacheable. Per-layer
  K/V rings and a per-slot hidden ring ride alongside the raw/token
  rings; the advance embeds the new tubelets, attends ONLY their
  queries against the cached window K/V (the band is computed on
  ABSOLUTE slot indices from a traced position counter, so ring
  wraparound can never alias a future slot), writes the new K/V/hidden
  back, and reads the label from the hidden ring — O(s·T) attention
  instead of O(T^2) trunk recompute, zero steady-state recompiles.
  Positional codes are RING-SLOT-stable ((abs_slot mod T')·hw +
  spatial), which at establish coincides with ordinary window order.
  ``trunk="full"`` (the default) is byte-for-byte today's token-ring
  graph. With ``serve.quantization=int8`` the K/V rings are stored
  int8 with per-token-row scales (serving/quantize.quantize_kv).
- **stem ring** (`MViT`): a true token seam for the overlapping
  (3,7,7)/(2,4,4) patch stem — its temporal receptive field is one
  frame of left halo, which the raw ring supplies. The advance writes
  the new frames, gathers the halo frame from the ring, runs the stem
  conv VALID-in-time over [halo, new frames], caches the resulting
  pre-positional stem-token slots, and re-enters the trunk via
  ``MViT.apply(..., from_stem=True)`` (learned pos_embed added at
  trunk time in window order). Steady-state advances see the REAL
  halo frame where one-shot `predict` zero-pads the window edge, so
  the parity oracle is `full_recompute_history` (replay over the
  whole stream), not the one-shot window. causal/windowed trunks are
  refused for MViT: its pooling attention mixes time through (3,·,·)
  conv kernels at every stage — there is no causal KV seam.
- **dual-rate rings** (`SlowFast`): two coupled rings — the fast ring
  holds every frame, the slow ring every alpha-th. Validation pins
  ``stride % alpha == 0`` so both rings advance in lock-step and the
  slow window is always the phase-0 subsample ``window[::alpha]`` of
  the fast window (slide-stable under streaming; this is the serving
  convention — PackPathway's truncated-linspace train-time sampling
  does not slide). Both rings are raw frames, hence weight-independent
  and adopted as-is across a hot-swap.

Parity contract: the incremental logits match `InferenceEngine.predict`
over the assembled host window (`full_recompute`) for the exact-window
families (frames / tokens-full / dual), and match the masked replay
over the whole stream history (`full_recompute_history`) for the
KV-trunk and stem families — gated in the bench STREAM lane and
tests/test_zstream.py + tests/test_zkvcache.py.

Compile discipline: advance/establish functions are jitted per
(kind, geometry, stride, bucket) and cached forever; session slots,
write offsets and the KV position counter are TRACED arguments, so
steady-state streaming touches zero new executables
(`compiled_stream_cache_sizes` is the RecompileGuard-style probe the
bench lane asserts flat).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from pytorchvideo_accelerate_tpu.obs import memory as obs_memory
from pytorchvideo_accelerate_tpu.streaming.session import (
    SessionAdmissionError,
    SessionError,
    SessionTable,
    SessionUnknownError,
)
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

logger = get_logger("pva_tpu")

# compiled stream-executable bound, same rationale as the engine's
# MAX_COMPILED_KEYS: every (geometry, stride, bucket) costs a synchronous
# compile + permanent executable memory
MAX_STREAM_KEYS = 64

TRUNK_MODES = ("full", "causal", "windowed")


def _np_dtype(name: str):
    return np.dtype(name)


@shared_state("_pools", "_fns", "_committed", benign={
    "_tok_meta": "written once at construction, read-only afterwards"})
class StreamingEngine:
    """Session-stateful wrapper around one `InferenceEngine`.

    Presents the engine surface the scheduler/hot-swap stack already
    speaks (`predict`/`buckets`/`warmup`/`compiled_keys` delegate to the
    wrapped engine) plus the session surface (`advance_batch`,
    `end_session`, `carry_state_from`). `supports_sessions` is the
    capability flag the scheduler/server check before routing session
    traffic."""

    supports_sessions = True

    def __init__(self, engine, *, session_budget_mb: float = 256.0,
                 session_ttl_s: float = 120.0, retry_after_s: float = 1.0,
                 registry=None, name: str = "stream",
                 trunk: str = "full", attn_window: int = 0):
        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.models import VideoMAEClassifier
        from pytorchvideo_accelerate_tpu.models.mvit import MViT
        from pytorchvideo_accelerate_tpu.models.slowfast import SlowFast

        self.engine = engine
        self.name = name
        self.session_budget_bytes = int(session_budget_mb * 1e6)
        self.table = SessionTable(ttl_s=session_ttl_s,
                                  retry_after_s=retry_after_s,
                                  registry=registry, name=name)
        self._lock = make_lock("StreamingEngine._lock")
        # pool_key -> {ring name: device array, "cap": int, "bytes": int}
        self._pools: Dict[tuple, Dict[str, Any]] = {}
        self._committed = 0  # declared ring-pool bytes against the budget
        # MemoryLedger component for this engine's ring pools
        # (docs/OBSERVABILITY.md § memory ledger)
        self._mem_component = f"stream_rings:{name}"
        self._fns: Dict[tuple, Any] = {}  # (op, kind, geom, stride, bucket)
        model = engine.model
        if isinstance(model, VideoMAEClassifier):
            self.kind = "tokens"
            tt, p, _ = model.tubelet
            self._tok_meta = {"tt": int(tt), "p": int(p),
                              "dim": int(model.dim),
                              "dtype": model.dtype}
        elif isinstance(model, MViT):
            self.kind = "stem"
            kt = int(model.patch_kernel[0])
            if kt % 2 == 0:
                raise SessionError(
                    "stem-ring streaming needs an odd temporal patch "
                    f"kernel (halo = kt//2 frames each side); got {kt}")
            self._tok_meta = {"ts": int(model.patch_stride[0]),
                              "halo": kt // 2,
                              "kernel": tuple(int(k) for k in model.patch_kernel),
                              "stride_sp": tuple(int(s) for s in model.patch_stride),
                              "dim": int(model.embed_dim),
                              "dtype": model.dtype}
        elif isinstance(model, SlowFast) \
                or engine.model_name.startswith("slowfast"):
            self.kind = "dual"
            self._tok_meta = {"alpha": int(getattr(model, "alpha", 4))}
        else:
            self.kind = "frames"
            self._tok_meta = None

        self.trunk = str(trunk)
        self.attn_window = int(attn_window)
        self._kv_meta: Optional[dict] = None
        if self.trunk not in TRUNK_MODES:
            raise SessionError(
                f"unknown stream trunk {trunk!r}; expected one of "
                f"{TRUNK_MODES} (serve.stream_trunk)")
        if self.trunk != "full":
            if self.kind != "tokens":
                reason = {
                    "stem": "MViT's pooling attention mixes time through "
                            "(3,·,·) conv kernels at every stage — there "
                            "is no causal KV seam",
                    "dual": "slowfast's lateral time-strided fusion convs "
                            "mix time globally",
                    "frames": "3-D conv trunks mix time globally",
                }[self.kind]
                raise SessionError(
                    f"stream trunk {self.trunk!r} needs a "
                    "VideoMAEClassifier token seam; "
                    f"{engine.model_name!r} does not have one ({reason}) "
                    "— serve stream_trunk=full "
                    "(docs/SERVING.md § trunk-reuse)")
            if model.attention_backend != "dense":
                raise SessionError(
                    f"stream trunk {self.trunk!r} runs banded-mask "
                    "attention, which only the 'dense' backend lowers "
                    f"(model.attention={model.attention_backend!r}) — "
                    "see ops/attention.dot_product_attention")
            if self.trunk == "windowed" and self.attn_window < 1:
                # default the band width from the served model's own
                # finetune knob (the recipe: finetune with
                # --model.attn_mask windowed --model.attn_window W, then
                # serve --serve.stream_trunk windowed)
                self.attn_window = int(getattr(model, "attn_window", 0))
            if self.trunk == "windowed" and self.attn_window < 1:
                raise SessionError(
                    "stream trunk 'windowed' needs attn_window >= 1 "
                    "(temporal slots; pass attn_window= or serve a model "
                    "finetuned with --model.attn_window)")
            self._kv_meta = {"depth": int(model.depth),
                             "heads": int(model.num_heads)}

        names = ["raw"]
        if self.kind == "tokens":
            names.append("tok")
            if self.trunk != "full":
                names.append("kv")
                if self.quantization == "int8":
                    names.append("kv_scale")
                names.append("hid")
        elif self.kind == "stem":
            names.append("stem")
        elif self.kind == "dual":
            names.append("slow")
        self._ring_names = tuple(names)
        self._jnp = jnp

    # --- delegated engine surface ----------------------------------------

    @property
    def buckets(self):
        return self.engine.buckets

    @property
    def mesh(self):
        return self.engine.mesh

    @property
    def model(self):
        return self.engine.model

    @property
    def model_name(self):
        return self.engine.model_name

    @property
    def num_classes(self):
        return self.engine.num_classes

    @property
    def input_dtype(self):
        return self.engine.input_dtype

    @property
    def stats(self):
        return self.engine.stats

    @property
    def quantization(self):
        return getattr(self.engine, "quantization", "off")

    @property
    def compiled_keys(self):
        return self.engine.compiled_keys

    def bucket_for(self, n: int) -> int:
        return self.engine.bucket_for(n)

    def predict(self, batch):
        return self.engine.predict(batch)

    def warmup(self, sample_clip) -> None:
        self.engine.warmup(sample_clip)

    # --- geometry ---------------------------------------------------------

    @staticmethod
    def geom_key(window: int, h: int, w: int, c: int, dtype: str) -> tuple:
        return (int(window), int(h), int(w), int(c), str(dtype))

    def _band_width(self, geom: tuple) -> int:
        """Temporal band width W in token slots: T' for causal (plain
        causality), the model's attn_window for windowed."""
        m = self._tok_meta
        return (geom[0] // m["tt"]) if self.trunk == "causal" \
            else self.attn_window

    def _stem_hw(self, geom: tuple) -> tuple:
        """Stem-token spatial grid (h', w') for one geometry — the SAME
        padded-conv arithmetic the model's patch_embed performs."""
        m = self._tok_meta
        _, kh, kw = m["kernel"]
        _, sh, sw = m["stride_sp"]
        _, h, w, _, _ = geom
        hh = (h + 2 * (kh // 2) - kh) // sh + 1
        ww = (w + 2 * (kw // 2) - kw) // sw + 1
        return hh, ww

    def ring_bytes(self, geom: tuple) -> int:
        """Device bytes ONE session's ring(s) cost — the unit of the HBM
        session budget."""
        t, h, w, c, dtype = geom
        total = t * h * w * c * _np_dtype(dtype).itemsize
        if self.kind == "tokens":
            m = self._tok_meta
            itemsize = np.dtype(
                self._jnp.zeros((), m["dtype"]).dtype).itemsize
            tn = t // m["tt"]
            hw = (h // m["p"]) * (w // m["p"])
            total += tn * hw * m["dim"] * itemsize
            if self.trunk != "full":
                kv_elems = self._kv_meta["depth"] * 2 * tn * hw
                if self.quantization == "int8":
                    total += kv_elems * m["dim"] + kv_elems * 4  # q8 + scale
                else:
                    total += kv_elems * m["dim"] * itemsize
                total += tn * m["dim"] * itemsize  # hidden ring
        elif self.kind == "stem":
            m = self._tok_meta
            itemsize = np.dtype(
                self._jnp.zeros((), m["dtype"]).dtype).itemsize
            hh, ww = self._stem_hw(geom)
            total += (t // m["ts"]) * hh * ww * m["dim"] * itemsize
        elif self.kind == "dual":
            total += (t // self._tok_meta["alpha"]) * h * w * c \
                * _np_dtype(dtype).itemsize
        return total

    def advance_h2d_bytes(self, geom: tuple, stride: int) -> int:
        """Host->device payload bytes per incremental advance (exact)."""
        _, h, w, c, dtype = geom
        return stride * h * w * c * _np_dtype(dtype).itemsize

    def full_h2d_bytes(self, geom: tuple) -> int:
        """Host->device payload bytes per full-window recompute (exact)."""
        t, h, w, c, dtype = geom
        return t * h * w * c * _np_dtype(dtype).itemsize

    def _validate(self, geom: tuple, stride: int) -> None:
        t, h, w, c, _ = geom
        if stride <= 0 or t % stride != 0:
            raise SessionError(
                f"stride {stride} must divide the window length {t} "
                "(ring writes must never wrap mid-advance)")
        if self.kind == "tokens":
            m = self._tok_meta
            if stride % m["tt"] != 0:
                raise SessionError(
                    f"stride {stride} must be a multiple of the model's "
                    f"temporal tubelet {m['tt']} (token-ring granularity)")
            if t % m["tt"] or h % m["p"] or w % m["p"]:
                raise SessionError(
                    f"window geometry {(t, h, w)} does not tile the "
                    f"tubelet {(m['tt'], m['p'], m['p'])}")
            if self.trunk == "windowed" and self.attn_window > t // m["tt"]:
                raise SessionError(
                    f"attn_window {self.attn_window} exceeds the window's "
                    f"{t // m['tt']} token slots — a band wider than the "
                    "ring would attend evicted state")
        elif self.kind == "stem":
            m = self._tok_meta
            if stride % m["ts"] or t % m["ts"]:
                raise SessionError(
                    f"stride {stride} / window {t} must be multiples of "
                    f"the stem's temporal stride {m['ts']} (stem-ring "
                    "granularity)")
            kt = m["kernel"][0]
            if (m["halo"] + stride - kt) % m["ts"] \
                    or (m["halo"] + stride - kt) // m["ts"] + 1 \
                    != stride // m["ts"]:
                raise SessionError(
                    f"stride {stride} does not align the stem conv "
                    f"(kernel {kt}, stride {m['ts']}, halo {m['halo']})")
        elif self.kind == "dual":
            alpha = self._tok_meta["alpha"]
            if stride % alpha or t % alpha:
                raise SessionError(
                    f"stride {stride} / window {t} must be multiples of "
                    f"the slowfast alpha {alpha} — the slow ring advances "
                    "in lock-step at 1/alpha rate")

    # --- pools ------------------------------------------------------------

    def _pool(self, geom: tuple) -> Dict[str, Any]:
        """Get-or-create the ring pool for `geom` (replicated over the
        engine's mesh — per-replica single-device meshes are the fleet
        pattern, so replication is free there; a multi-device serving
        mesh pays HBM for simplicity, documented).

        The session budget is GLOBAL across pools: each new geometry's
        pool is sized from the budget's REMAINING bytes (first geometry
        gets most of it), and a geometry whose pool would hold zero
        sessions is refused — a client fanning out novel window shapes
        must exhaust the budget into 503s, never allocate
        budget-per-shape until the device OOMs."""
        with self._lock:
            pool = self._pools.get(geom)
            if pool is not None:
                return pool
            ring = max(self.ring_bytes(geom), 1)
            committed, src = self._budget_committed()
            remaining = self.session_budget_bytes - committed
            cap = remaining // ring
            if cap < 1:
                raise SessionAdmissionError(
                    f"session budget exhausted ({self.name}: "
                    f"{committed / 1e6:.0f} MB committed ({src}) of "
                    f"{self.session_budget_bytes / 1e6:.0f} MB; a "
                    f"{ring / 1e6:.1f} MB/session pool for {geom} does "
                    "not fit); retry later",
                    retry_after_s=self.table.retry_after_s)
            # +1 scratch slot: padded launch rows write here, never into a
            # leased ring
            pool = {"cap": int(cap), "bytes": int(cap + 1) * ring}
            for nm in self._ring_names:
                pool[nm] = self._alloc_ring(nm, geom, int(cap) + 1)
            # ledger the ACTUAL device bytes (padding/dtype promotion make
            # them drift from the ring_bytes estimate — the drift gauge's
            # whole point); admission above consumes the measured figure
            # on hosts that measure
            pool["measured_bytes"] = sum(
                int(getattr(pool[nm], "nbytes", 0))
                for nm in self._ring_names)
            obs_memory.register(self._mem_component,
                                pool["measured_bytes"],
                                declared=pool["bytes"])
            self._pools[geom] = pool
            self._committed += pool["bytes"]
            self.table.register_pool(geom, int(cap))
            logger.info(
                "stream: pool %s = %d session slots (+1 scratch), "
                "%.1f MB/session (%s); %.0f/%.0f MB budget committed",
                geom, cap, ring / 1e6, "+".join(self._ring_names),
                self._committed / 1e6, self.session_budget_bytes / 1e6)
            return pool

    def _budget_committed(self) -> tuple:
        """(bytes, source) the admission math diffs against the budget:
        *measured* ledger bytes on a host whose backend exposes
        `memory_stats()`, the declared `ring_bytes` estimates otherwise
        (the documented CPU/test fallback — estimates admit, but they
        never impersonate device bytes)."""
        led = obs_memory.get_ledger()
        if led is not None:
            measured = led.measured_bytes(self._mem_component)
            if measured is not None:
                return measured, "measured"
        return self._committed, "declared"

    def _replicated(self, arr):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(  # pva: disable=ledger-discipline -- generic H2D helper; retained rings are ledgered by their owning scope (_pool_for registers the pool bytes), other callers move transient launch rows
            arr, NamedSharding(self.mesh, P()))

    def _alloc_ring(self, name: str, geom: tuple, rows: int):
        t, h, w, c, dtype = geom
        m = self._tok_meta
        if name == "raw":
            shape, dt = (rows, t, h, w, c), _np_dtype(dtype)
        elif name == "tok":
            shape = (rows, t // m["tt"],
                     (h // m["p"]) * (w // m["p"]), m["dim"])
            dt = np.dtype(self._jnp.zeros((), m["dtype"]).dtype)
        elif name == "kv":
            tn, hw = t // m["tt"], (h // m["p"]) * (w // m["p"])
            shape = (rows, self._kv_meta["depth"], 2, tn, hw, m["dim"])
            dt = (np.int8 if self.quantization == "int8"
                  else np.dtype(self._jnp.zeros((), m["dtype"]).dtype))
        elif name == "kv_scale":
            tn, hw = t // m["tt"], (h // m["p"]) * (w // m["p"])
            shape = (rows, self._kv_meta["depth"], 2, tn, hw)
            dt = np.float32
        elif name == "hid":
            shape = (rows, t // m["tt"], m["dim"])
            dt = np.dtype(self._jnp.zeros((), m["dtype"]).dtype)
        elif name == "stem":
            hh, ww = self._stem_hw(geom)
            shape = (rows, t // m["ts"], hh, ww, m["dim"])
            dt = np.dtype(self._jnp.zeros((), m["dtype"]).dtype)
        elif name == "slow":
            shape = (rows, t // m["alpha"], h, w, c)
            dt = _np_dtype(dtype)
        else:
            raise SessionError(f"unknown ring {name!r}")
        return self._replicated(np.zeros(shape, dt))

    # --- compiled steps ---------------------------------------------------

    def _forward_windows(self, params, bstats, windows):
        """The wrapped engine's exact forward over in-graph windows
        (B, T, H, W, C): constrain -> normalize -> model — the op sequence
        of `InferenceEngine._make_forward`, so incremental logits carry
        serving parity by construction."""
        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.serving.quantize import (
            dequantize_tree,
        )
        from pytorchvideo_accelerate_tpu.trainer.steps import (
            _constrain_batch,
            device_normalize_batch,
            model_inputs,
            multiview_logits,
        )

        eng = self.engine
        if self.quantization == "int8":
            params = dequantize_tree(params, eng._compute_dtype)
        batch = _constrain_batch({"video": windows}, eng.mesh,
                                 leading_micro=False)
        batch = device_normalize_batch(batch, eng._device_normalize)
        logits = multiview_logits(
            lambda x: eng.model.apply(
                {"params": params, "batch_stats": bstats}, x, train=False),
            model_inputs(batch))
        return logits.astype(jnp.float32)

    def _forward_dual(self, params, bstats, slow_w, fast_w):
        """`_forward_windows` for the SlowFast pathway pair — the same
        constrain -> normalize -> model sequence over the {"slow",
        "fast"} batch `InferenceEngine.predict` serves, so dual-ring
        logits carry serving parity by construction."""
        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.serving.quantize import (
            dequantize_tree,
        )
        from pytorchvideo_accelerate_tpu.trainer.steps import (
            _constrain_batch,
            device_normalize_batch,
            model_inputs,
            multiview_logits,
        )

        eng = self.engine
        if self.quantization == "int8":
            params = dequantize_tree(params, eng._compute_dtype)
        batch = _constrain_batch({"slow": slow_w, "fast": fast_w},
                                 eng.mesh, leading_micro=False)
        batch = device_normalize_batch(batch, eng._device_normalize)
        logits = multiview_logits(
            lambda x: eng.model.apply(
                {"params": params, "batch_stats": bstats}, x, train=False),
            model_inputs(batch))
        return logits.astype(jnp.float32)

    def _embed_tokens(self, params, frames):
        """Patch-embed (B, t, H, W, C) frames -> (B, t/tt, hw, dim)
        pre-positional tokens: normalize (u8 engines) then the
        classifier's own CubeEmbed applied from its param subtree — each
        tubelet's token is a pure function of its own pixels, which is
        the whole reason the token ring is exact. `params` must already
        be dequantized (the compiled step dequantizes once at its top)."""
        from pytorchvideo_accelerate_tpu.models.videomae import CubeEmbed
        from pytorchvideo_accelerate_tpu.trainer.steps import (
            device_normalize_batch,
        )

        m = self._tok_meta
        model = self.engine.model
        x = device_normalize_batch({"video": frames},
                                   self.engine._device_normalize)["video"]
        tokens, (t, h, w) = CubeEmbed(
            model.dim, model.tubelet, model.dtype, name="patch_embed",
        ).apply({"params": params["encoder"]["patch_embed"]}, x)
        return tokens.reshape(tokens.shape[0], t, h * w, m["dim"])

    def _forward_tokens(self, params, tok_windows):
        """Trunk from cached tokens: + window-order positional embedding
        -> ViT blocks -> mean-pool -> fc_norm -> head, mirroring
        `VideoMAEClassifier.__call__` op for op (final_norm=False,
        deterministic dropout). `params` arrive dequantized."""
        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.models.videomae import (
            ViTBlock,
            sincos_pos_embed,
        )
        from pytorchvideo_accelerate_tpu.parallel.sharding import (
            constrain_block,
        )

        model = self.engine.model
        b, t, hw, dim = tok_windows.shape
        tokens = tok_windows.reshape(b, t * hw, dim)
        pos = jnp.asarray(sincos_pos_embed(t * hw, dim))[None]
        tokens = tokens + pos.astype(tokens.dtype)
        # a banded-trunk backbone (model.attn_mask, the streaming
        # finetune knob) keeps its band under trunk="full" too —
        # `full` means "recompute the whole trunk", never "drop the
        # mask the model was finetuned with"
        mask = None
        if getattr(model, "attn_mask", "none") != "none":
            from pytorchvideo_accelerate_tpu.ops.attention import (
                temporal_band_mask,
            )

            width = t if model.attn_mask == "causal" else model.attn_window
            mask = temporal_band_mask(t, hw, width)[None, None]
        for i in range(model.depth):
            tokens = ViTBlock(
                dim=model.dim, num_heads=model.num_heads,
                attention_backend=model.attention_backend,
                context_mesh=model.context_mesh, dtype=model.dtype,
            ).apply({"params": params["encoder"][f"block{i}"]}, tokens,
                    mask)
            tokens = constrain_block(tokens,
                                     getattr(model, "shard_mesh", None))
        return self._head_logits(params, tokens.mean(axis=1))

    def _head_logits(self, params, feat):
        """The classifier epilogue — fc_norm -> head in the engine's
        f32-island policy — shared by every token/KV trunk path so the
        full and incremental graphs read one definition of the head."""
        import jax.numpy as jnp
        from flax import linen as nn

        from pytorchvideo_accelerate_tpu.precision import f32_island

        model = self.engine.model
        feat = nn.LayerNorm(dtype=model.dtype).apply(
            {"params": params["fc_norm"]}, feat)
        logits = nn.Dense(model.num_classes, dtype=jnp.float32).apply(
            {"params": params["head"]}, f32_island(feat))
        return logits.astype(jnp.float32)

    # --- KV trunk (causal / windowed) -------------------------------------

    def _block_fwd(self, bp, x, mask, kv_cache=None):
        """One ViT block hand-rolled from its param subtree, exposing the
        per-layer K/V the KV rings cache. Mirrors
        models/videomae.ViTBlock op for op (pre-LN, erf GELU, the same
        `dot_product_attention` router) — the vs-classifier parity test
        in tests/test_zkvcache.py holds this to the serving tolerance.

        `kv_cache=(k, v)` (B, Nc, dim) switches to the INCREMENTAL
        formulation: x's queries attend [cache ++ x's own keys]; `mask`
        must then be the band over that concatenated key order. Returns
        (x_out, k, v) where k/v cover ONLY x's own tokens — exactly what
        gets written back into the ring."""
        import jax.numpy as jnp
        from flax import linen as nn

        from pytorchvideo_accelerate_tpu.ops.attention import (
            dot_product_attention,
        )

        model = self.engine.model
        dim, heads = model.dim, model.num_heads
        hd = dim // heads
        dt = model.dtype
        y = nn.LayerNorm(dtype=dt).apply({"params": bp["norm1"]}, x)
        qkv = nn.Dense(3 * dim, dtype=dt).apply({"params": bp["qkv"]}, y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        kk, vv = k, v
        if kv_cache is not None:
            kk = jnp.concatenate([kv_cache[0].astype(k.dtype), k], axis=1)
            vv = jnp.concatenate([kv_cache[1].astype(v.dtype), v], axis=1)
        b, nq = q.shape[:2]
        nk = kk.shape[1]
        attn = dot_product_attention(
            q.reshape(b, nq, heads, hd), kk.reshape(b, nk, heads, hd),
            vv.reshape(b, nk, heads, hd),
            backend=model.attention_backend, mesh=model.context_mesh,
            mask=mask,
        ).reshape(b, nq, dim)
        x = x + nn.Dense(dim, dtype=dt).apply({"params": bp["proj"]}, attn)
        y = nn.LayerNorm(dtype=dt).apply({"params": bp["norm2"]}, x)
        y = nn.Dense(bp["mlp_fc1"]["kernel"].shape[-1], dtype=dt).apply(
            {"params": bp["mlp_fc1"]}, y)
        y = nn.gelu(y, approximate=False)
        x = x + nn.Dense(dim, dtype=dt).apply({"params": bp["mlp_fc2"]}, y)
        return x, k, v

    def _trunk_kv_full(self, params, tokens, slot_idx, window, ring_slots):
        """Masked trunk over a whole window of tokens in LOGICAL
        (oldest-first) order -> (per-layer KV (B, L, 2, tn, hw, dim) in
        the same logical order, per-slot hidden means (B, tn, dim)).

        `slot_idx` (B, tn) gives each logical slot's RING-SLOT-stable
        position index ((abs_slot mod T')); positional codes are gathered
        from the T'*hw table by that index, so cached K/V stay valid as
        the ring rotates. At establish `slot_idx == arange(T')` — the
        ordinary window positions the finetuned backbone saw."""
        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.models.videomae import (
            sincos_pos_embed,
        )
        from pytorchvideo_accelerate_tpu.ops.attention import (
            temporal_band_mask,
        )
        from pytorchvideo_accelerate_tpu.parallel.sharding import (
            constrain_block,
        )

        model = self.engine.model
        b, tn, hw, dim = tokens.shape
        pos = jnp.asarray(sincos_pos_embed(ring_slots * hw, dim))
        pos_idx = (slot_idx[..., None] * hw
                   + jnp.arange(hw, dtype=jnp.int32)[None, None, :])
        x = tokens.reshape(b, tn * hw, dim) + jnp.take(
            pos, pos_idx.reshape(b, tn * hw), axis=0).astype(tokens.dtype)
        mask = temporal_band_mask(tn, hw, window)[None, None]
        ks, vs = [], []
        for i in range(model.depth):
            x, k, v = self._block_fwd(
                params["encoder"][f"block{i}"], x, mask)
            x = constrain_block(x, getattr(model, "shard_mesh", None))
            ks.append(k)
            vs.append(v)
        depth = model.depth
        kv = jnp.stack([jnp.stack(ks, axis=1), jnp.stack(vs, axis=1)],
                       axis=2).reshape(b, depth, 2, tn, hw, dim)
        hid = x.reshape(b, tn, hw, dim).mean(axis=2)
        return kv, hid

    def _trunk_kv_step(self, params, new_tok, kv_cache, tpos, window,
                       ring_slots):
        """The incremental trunk: the ns NEW slots' queries against the
        cached ring K/V plus their own -> (new per-layer KV
        (B, L, 2, ns, hw, dim), new hidden means (B, ns, dim)).

        `tpos` (B,) int32 TRACED — the absolute index of the first new
        slot. The band mask is computed on absolute indices recovered
        from `tpos` (ring slot j holds abs `newest - ((newest - j) mod
        T')`), so slots being overwritten this advance (abs <= tpos - T')
        fall outside every query's band automatically: wraparound can
        never alias a future slot. `kv_cache` (B, L, 2, T', hw, dim)
        arrives dequantized in compute dtype."""
        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.models.videomae import (
            sincos_pos_embed,
        )
        from pytorchvideo_accelerate_tpu.ops.attention import (
            banded_time_mask,
        )
        from pytorchvideo_accelerate_tpu.parallel.sharding import (
            constrain_block,
        )

        model = self.engine.model
        b, ns, hw, dim = new_tok.shape
        tn = ring_slots
        j = jnp.arange(tn, dtype=jnp.int32)[None, :]
        newest = (tpos - 1)[:, None]
        k_abs = newest - ((newest - j) % tn)                     # (B, tn)
        q_abs = tpos[:, None] + jnp.arange(ns, dtype=jnp.int32)[None, :]
        band = banded_time_mask(
            q_abs, jnp.concatenate([k_abs, q_abs], axis=1), window)
        mask = jnp.repeat(jnp.repeat(band, hw, axis=1), hw, axis=2)[:, None]
        pos = jnp.asarray(sincos_pos_embed(tn * hw, dim))
        pos_idx = ((q_abs % tn)[..., None] * hw
                   + jnp.arange(hw, dtype=jnp.int32)[None, None, :])
        x = new_tok.reshape(b, ns * hw, dim) + jnp.take(
            pos, pos_idx.reshape(b, ns * hw), axis=0).astype(new_tok.dtype)
        ks, vs = [], []
        for i in range(model.depth):
            cache = (kv_cache[:, i, 0].reshape(b, tn * hw, dim),
                     kv_cache[:, i, 1].reshape(b, tn * hw, dim))
            x, k, v = self._block_fwd(
                params["encoder"][f"block{i}"], x, mask, kv_cache=cache)
            x = constrain_block(x, getattr(model, "shard_mesh", None))
            ks.append(k)
            vs.append(v)
        depth = model.depth
        new_kv = jnp.stack([jnp.stack(ks, axis=1), jnp.stack(vs, axis=1)],
                           axis=2).reshape(b, depth, 2, ns, hw, dim)
        new_hid = x.reshape(b, ns, hw, dim).mean(axis=2)
        return new_kv, new_hid

    # --- MViT stem seam ---------------------------------------------------

    def _stem_embed(self, params, frames, temporal_pad):
        """Normalize raw frames and run MViT's patch-embed conv from its
        param subtree -> (B, t', h', w', embed_dim) pre-positional stem
        tokens. `temporal_pad`: the model's own (halo, halo) for
        establish/replay (fresh-stream zero halo at the very first
        frame), (0, 0) for the advance — the REAL halo frames ride at
        the front of `frames` there, gathered from the raw ring."""
        from flax import linen as nn

        from pytorchvideo_accelerate_tpu.trainer.steps import (
            device_normalize_batch,
        )

        m = self._tok_meta
        model = self.engine.model
        x = device_normalize_batch({"video": frames},
                                   self.engine._device_normalize)["video"]
        x = x.astype(model.dtype)
        _, kh, kw = m["kernel"]
        pad = [tuple(temporal_pad), (kh // 2, kh // 2), (kw // 2, kw // 2)]
        return nn.Conv(
            m["dim"], kernel_size=m["kernel"], strides=m["stride_sp"],
            padding=pad, dtype=model.dtype,
        ).apply({"params": params["patch_embed"]}, x)

    def _forward_stem(self, params, bstats, stem_windows):
        """Trunk re-entry from cached stem tokens: `MViT.apply(...,
        from_stem=True)` over the window-ordered (B, T', H', W', dim)
        token grid — pos_embed is added inside, in window order.
        `params` arrive dequantized."""
        import jax.numpy as jnp

        logits = self.engine.model.apply(
            {"params": params, "batch_stats": bstats}, stem_windows,
            train=False, from_stem=True)
        return logits.astype(jnp.float32)

    def _get_fn(self, op: str, geom: tuple, stride: int, bucket: int):
        key = (op, self.kind, geom, int(stride), int(bucket))
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                if len(self._fns) >= MAX_STREAM_KEYS:
                    raise SessionError(
                        f"engine already compiled {len(self._fns)} stream "
                        "geometries; refusing a new one (clients should "
                        "send the serving stream geometry)")
                fn = self._build_fn(op, geom, stride, bucket)
                self._fns[key] = fn
                logger.info("stream: compiling %s for %s stride=%d B=%d",
                            op, geom, stride, bucket)
        return fn

    def _build_fn(self, op: str, geom: tuple, stride: int, bucket: int):
        import jax
        import jax.numpy as jnp

        tokens = self.kind == "tokens"
        m = self._tok_meta
        names = self._ring_names
        nring = len(names)
        donate = tuple(range(2, 2 + nring))

        def dq(params):
            # token/stem-path dequant happens ONCE here: the embed and
            # the trunk both read the same fp view, and XLA fuses q*scale
            # into the weight reads exactly like the engine forward
            if self.quantization == "int8" and self.kind in ("tokens",
                                                             "stem"):
                from pytorchvideo_accelerate_tpu.serving.quantize import (
                    dequantize_tree,
                )

                return dequantize_tree(params, self.engine._compute_dtype)
            return params

        def write(pool, rows, slots, offs):
            """Write per-session rows into the donated pool at traced
            (slot, offset) — a sequential fori_loop of
            dynamic_update_slice, which XLA applies IN PLACE on the
            donated buffer: the update moves only the new rows' bytes,
            never whole rings (the gather-modify-scatter formulation
            copied every ring three times and cost more than the H2D it
            saved). Offsets never wrap because stride divides the
            window; scratch-slot duplicates are benign (sequential)."""
            def body(i, p):
                return jax.lax.dynamic_update_slice(
                    p, rows[i][None].astype(p.dtype),
                    (slots[i], offs[i]) + (0,) * (p.ndim - 2))

            return jax.lax.fori_loop(0, rows.shape[0], body, pool)

        def write_axis(pool, rows, slots, offs, axis):
            """`write` with the rolling offset on an arbitrary pool axis
            — the KV ring keeps its temporal slots at axis 3 of the
            (rows, L, 2, T', hw, dim) pool, so the per-advance write
            lands at (slot, :, :, off_t, ...)."""
            def body(i, p):
                start = [slots[i]] + [0] * (p.ndim - 1)
                start[axis] = offs[i]
                return jax.lax.dynamic_update_slice(
                    p, rows[i][None].astype(p.dtype), tuple(start))

            return jax.lax.fori_loop(0, rows.shape[0], body, pool)

        # --- KV-trunk token ops (causal / windowed) -----------------------
        if tokens and self.trunk != "full":
            from pytorchvideo_accelerate_tpu.serving.quantize import (
                dequantize_kv,
                quantize_kv,
            )

            tt = m["tt"]
            tn = geom[0] // tt
            window = self._band_width(geom)
            int8 = "kv_scale" in names

            def write_kv(rings_out, kv_new, hid_new, slots, toffs):
                """Quantize (int8 engines) and write one advance's new
                K/V + hidden slots into their rings."""
                if int8:
                    q8, sc = quantize_kv(kv_new)
                    rings_out["kv"] = write_axis(
                        rings_out["kv"], q8, slots, toffs, 3)
                    rings_out["kv_scale"] = write_axis(
                        rings_out["kv_scale"], sc, slots, toffs, 3)
                else:
                    rings_out["kv"] = write_axis(
                        rings_out["kv"], kv_new, slots, toffs, 3)
                rings_out["hid"] = write(
                    rings_out["hid"], hid_new, slots, toffs)

            if op == "establish":
                def fn(params, bstats, *args):
                    rings = dict(zip(names, args[:nring]))
                    windows, slots = args[nring], args[nring + 1]
                    params = dq(params)
                    zeros = jnp.zeros_like(slots)
                    rings["raw"] = write(rings["raw"], windows, slots,
                                         zeros)
                    new_tok = self._embed_tokens(params, windows)
                    rings["tok"] = write(rings["tok"], new_tok, slots,
                                         zeros)
                    slot_idx = jnp.broadcast_to(
                        jnp.arange(tn, dtype=jnp.int32),
                        (new_tok.shape[0], tn))
                    kv_new, hid_new = self._trunk_kv_full(
                        params, new_tok, slot_idx, window, tn)
                    write_kv(rings, kv_new, hid_new, slots, zeros)
                    logits = self._head_logits(params, hid_new.mean(axis=1))
                    return tuple(rings[nm] for nm in names) + (logits,)

                return jax.jit(fn, donate_argnums=donate)

            if op == "advance":
                def fn(params, bstats, *args):
                    rings = dict(zip(names, args[:nring]))
                    frames, slots, offs, tpos = args[nring:nring + 4]
                    params = dq(params)
                    rings["raw"] = write(rings["raw"], frames, slots, offs)
                    new_tok = self._embed_tokens(params, frames)
                    toffs = offs // tt
                    rings["tok"] = write(rings["tok"], new_tok, slots,
                                         toffs)
                    kv_rows = rings["kv"][slots]
                    if int8:
                        kv_rows = dequantize_kv(
                            kv_rows, rings["kv_scale"][slots],
                            self.engine.model.dtype)
                    new_kv, new_hid = self._trunk_kv_step(
                        params, new_tok, kv_rows, tpos, window, tn)
                    write_kv(rings, new_kv, new_hid, slots, toffs)
                    feat = rings["hid"][slots].mean(axis=1)
                    logits = self._head_logits(params, feat)
                    return tuple(rings[nm] for nm in names) + (logits,)

                return jax.jit(fn, donate_argnums=donate)

        # --- stem-ring ops (MViT token seam) ------------------------------
        if self.kind == "stem":
            ts, halo = m["ts"], m["halo"]

            if op == "establish":
                def fn(params, bstats, *args):
                    rings = dict(zip(names, args[:nring]))
                    windows, slots = args[nring], args[nring + 1]
                    params = dq(params)
                    zeros = jnp.zeros_like(slots)
                    rings["raw"] = write(rings["raw"], windows, slots,
                                         zeros)
                    new_stem = self._stem_embed(params, windows,
                                                (halo, halo))
                    rings["stem"] = write(rings["stem"], new_stem, slots,
                                          zeros)
                    logits = self._forward_stem(params, bstats, new_stem)
                    return tuple(rings[nm] for nm in names) + (logits,)

                return jax.jit(fn, donate_argnums=donate)

            if op == "advance":
                t = geom[0]
                ss = stride // ts

                def fn(params, bstats, *args):
                    rings = dict(zip(names, args[:nring]))
                    frames, slots, offs = args[nring:nring + 3]
                    params = dq(params)
                    rings["raw"] = write(rings["raw"], frames, slots, offs)
                    # the REAL left halo: the newest frames still in the
                    # ring before this write's offset (never overwritten
                    # by it — the write covers [off, off+stride))
                    halo_idx = (offs[:, None] - halo
                                + jnp.arange(halo, dtype=jnp.int32)[None,
                                                                    :]) % t
                    halo_frames = jax.vmap(
                        lambda r, hi: jnp.take(r, hi, axis=0)
                    )(rings["raw"][slots], halo_idx)
                    x = jnp.concatenate(
                        [halo_frames.astype(frames.dtype), frames], axis=1)
                    new_stem = self._stem_embed(params, x, (0, 0))
                    rings["stem"] = write(rings["stem"], new_stem, slots,
                                          offs // ts)
                    stem_windows = jax.vmap(
                        lambda r, o: jnp.roll(r, -(o // ts + ss), axis=0)
                    )(rings["stem"][slots], offs)
                    logits = self._forward_stem(params, bstats,
                                                stem_windows)
                    return tuple(rings[nm] for nm in names) + (logits,)

                return jax.jit(fn, donate_argnums=donate)

        # --- dual-rate ops (SlowFast) -------------------------------------
        if self.kind == "dual":
            alpha = m["alpha"]

            if op == "establish":
                def fn(params, bstats, *args):
                    rings = dict(zip(names, args[:nring]))
                    windows, slots = args[nring], args[nring + 1]
                    zeros = jnp.zeros_like(slots)
                    rings["raw"] = write(rings["raw"], windows, slots,
                                         zeros)
                    slow_w = windows[:, ::alpha]
                    rings["slow"] = write(rings["slow"], slow_w, slots,
                                          zeros)
                    logits = self._forward_dual(
                        params, bstats,
                        slow_w.astype(rings["slow"].dtype),
                        windows.astype(rings["raw"].dtype))
                    return tuple(rings[nm] for nm in names) + (logits,)

                return jax.jit(fn, donate_argnums=donate)

            if op == "advance":
                sstride = stride // alpha

                def fn(params, bstats, *args):
                    rings = dict(zip(names, args[:nring]))
                    frames, slots, offs = args[nring:nring + 3]
                    rings["raw"] = write(rings["raw"], frames, slots, offs)
                    rings["slow"] = write(rings["slow"], frames[:, ::alpha],
                                          slots, offs // alpha)
                    fast_w = jax.vmap(
                        lambda r, o: jnp.roll(r, -(o + stride), axis=0)
                    )(rings["raw"][slots], offs)
                    slow_w = jax.vmap(
                        lambda r, o: jnp.roll(r, -(o // alpha + sstride),
                                              axis=0)
                    )(rings["slow"][slots], offs)
                    logits = self._forward_dual(params, bstats, slow_w,
                                                fast_w)
                    return tuple(rings[nm] for nm in names) + (logits,)

                return jax.jit(fn, donate_argnums=donate)

        # --- frame-ring and full-trunk token ops (unchanged graphs) -------
        if op == "advance" and not tokens:
            def fn(params, bstats, raw, frames, slots, offs):
                raw = write(raw, frames, slots, offs)
                windows = jax.vmap(
                    lambda r, o: jnp.roll(r, -(o + stride), axis=0)
                )(raw[slots], offs)
                return raw, self._forward_windows(params, bstats, windows)

            return jax.jit(fn, donate_argnums=(2,))

        if op == "advance" and tokens:
            tstride = stride // m["tt"]

            def fn(params, bstats, raw, tok, frames, slots, offs):
                params = dq(params)
                raw = write(raw, frames, slots, offs)
                new_tok = self._embed_tokens(params, frames)
                tok = write(tok, new_tok, slots, offs // m["tt"])
                tok_windows = jax.vmap(
                    lambda r, o: jnp.roll(r, -(o // m["tt"] + tstride),
                                          axis=0))(tok[slots], offs)
                return (raw, tok,
                        self._forward_tokens(params, tok_windows))

            return jax.jit(fn, donate_argnums=(2, 3))

        if op == "establish" and not tokens:
            def fn(params, bstats, raw, windows, slots):
                raw = write(raw, windows, slots, jnp.zeros_like(slots))
                # the freshly-written rings ARE the input windows (offset
                # 0): forward from the input, no gather-back needed
                return raw, self._forward_windows(
                    params, bstats, windows.astype(raw.dtype))

            return jax.jit(fn, donate_argnums=(2,))

        if op == "establish" and tokens:
            def fn(params, bstats, raw, tok, windows, slots):
                params = dq(params)
                zeros = jnp.zeros_like(slots)
                raw = write(raw, windows, slots, zeros)
                new_tok = self._embed_tokens(params, windows)
                tok = write(tok, new_tok, slots, zeros)
                return raw, tok, self._forward_tokens(params, new_tok)

            return jax.jit(fn, donate_argnums=(2, 3))

        raise SessionError(f"unknown stream op {op!r}")

    # --- the session surface ---------------------------------------------

    def advance_batch(self, items: List[dict]) -> List[Any]:
        """Score one launch of session advances. Each item:
        ``{"sid": str, "frames": (s, H, W, C), "window": optional
        (T, H, W, C) resendable window, "end": bool}``.

        Routing per item: a session this replica holds advances
        incrementally; an unknown/mismatched one re-establishes
        DETERMINISTICALLY from the item's resendable window (how replica
        death and affinity re-routes stay client-invisible) or fails
        with `SessionUnknownError` when no window rides along. Items are
        grouped into same-(geometry, stride) compiled launches; duplicate
        sids within one call are serialized into waves (a ring must never
        be read and written by two rows of one launch). Returns one entry
        PER ITEM in order: fp32 logits, or the Exception that item earned
        — a malformed item must fail ITS future, never its co-batched
        neighbours'."""
        self.table.sweep()
        results: List[Any] = [None] * len(items)
        pending = list(enumerate(items))
        while pending:
            wave: List[tuple] = []
            seen: set = set()
            rest: List[tuple] = []
            for idx, item in pending:
                sid = str(item.get("sid", ""))
                if sid in seen:
                    rest.append((idx, item))
                else:
                    seen.add(sid)
                    wave.append((idx, item))
            self._run_wave(wave, results)
            pending = rest
        for item in items:
            if item.get("end"):
                self.table.end(str(item.get("sid", "")))
        return results

    def _classify(self, item: dict) -> tuple:
        """-> (mode, sid, payload np, geom, stride) for one item; decides
        advance vs re-establish and validates against the session/ring
        contract."""
        sid = str(item.get("sid") or "")
        if not sid:
            raise SessionError("stream item carries no session id")
        frames = item.get("frames")
        window = item.get("window")
        if frames is None and window is None:
            raise SessionError(f"stream item for {sid!r} carries neither "
                               "frames nor a window")
        dtype = self.input_dtype
        if window is not None:
            window = np.asarray(window, dtype)
            if window.ndim != 4:
                raise SessionError(
                    f"window for {sid!r} must be (T, H, W, C), got "
                    f"{window.shape}")
        if frames is not None:
            frames = np.asarray(frames, dtype)
            if frames.ndim != 4:
                raise SessionError(
                    f"frames for {sid!r} must be (s, H, W, C), got "
                    f"{frames.shape}")
        state = self.table.get(sid)
        if state is not None and frames is not None:
            geom = state.pool_key
            if (frames.shape[0] == state.stride
                    and tuple(frames.shape[1:]) == tuple(geom[1:4])):
                return ("advance", sid, frames, geom, state.stride)
            # stride/geometry drift: fall through to re-establish (window
            # required — silently writing drifted frames would corrupt
            # the ring)
        if window is None:
            raise SessionUnknownError(
                f"session {sid!r} is not established on this replica and "
                "the request carries no resendable window")
        t, h, w, c = window.shape
        stride = int(item.get("stride") or
                     (frames.shape[0] if frames is not None else 0) or 0)
        if stride <= 0:
            raise SessionError(
                f"establish for {sid!r} needs a stride (frames payload or "
                "explicit 'stride')")
        geom = self.geom_key(t, h, w, c, dtype)
        self._validate(geom, stride)
        return ("establish", sid, window, geom, stride)

    def _run_wave(self, wave: List[tuple], results: List[Any]) -> None:
        """Group one duplicate-free wave by (mode, geom, stride) and run
        each group as one bucketed compiled launch. Per-item
        classification/admission failures land in `results` as
        exceptions; the rest of the wave still launches."""
        groups: Dict[tuple, List[tuple]] = {}
        for idx, item in wave:
            try:
                mode, sid, payload, geom, stride = self._classify(item)
            except Exception as e:  # noqa: BLE001 - per-item verdict
                results[idx] = e
                continue
            groups.setdefault((mode, geom, stride), []).append(
                (idx, sid, payload))
        for (mode, geom, stride), rows in groups.items():
            try:
                if mode == "establish":
                    self._launch_establish(geom, stride, rows, results)
                else:
                    self._launch_advance(geom, stride, rows, results)
            except Exception as e:  # noqa: BLE001 - contain to THIS group
                # a group-level failure (MAX_STREAM_KEYS refusal for a
                # novel geometry, a compile error) must fail the group
                # that caused it — never the other geometries co-batched
                # in the same flush
                for idx, _, _ in rows:
                    if results[idx] is None:
                        results[idx] = e

    def _stack(self, rows, pool) -> tuple:
        """Pad a group to its bucket: payload rows stacked with zero
        rows, slots padded with the pool's scratch row, offsets 0."""
        n = len(rows)
        bucket = self.bucket_for(n)
        payload = np.stack([p for _, _, p in rows])
        if bucket > n:
            pad = np.zeros((bucket - n,) + payload.shape[1:], payload.dtype)
            payload = np.concatenate([payload, pad], axis=0)
        return payload, bucket, pool["cap"]

    def _tpos_of(self, state) -> int:
        """A session's absolute token-slot position counter: the index
        the NEXT advance's first new slot will carry. Establish seeds
        slots 0..T'-1, so tpos == T' there; the `tpos % T' == off//tt`
        invariant is what lets the hot-swap rebuild recover every slot's
        absolute index from the adopted table."""
        tt = self._tok_meta["tt"]
        return (state.window + state.frames_seen) // tt

    def _launch_establish(self, geom, stride, rows, results) -> None:
        pool = self._pool(geom)
        live = []
        states = []
        for idx, sid, payload in rows:
            try:
                # the admission decision (TTL eviction vs 503) happens
                # here, per session, against the HBM budget
                states.append(self.table.establish(
                    sid, geom, stride=stride, window=geom[0]))
                live.append((idx, sid, payload))
            except Exception as e:  # noqa: BLE001 - per-item verdict
                results[idx] = e
        if not live:
            return
        payload, bucket, scratch = self._stack(live, pool)
        slots = np.asarray([s.slot for s in states]
                           + [scratch] * (bucket - len(live)), np.int32)
        fn = self._get_fn("establish", geom, stride, bucket)
        logits = self._guarded_call(fn, geom, pool, payload, slots, None)
        for i, (idx, sid, _) in enumerate(live):
            # establish resets the write offset to 0; the committed
            # position is "window seen, next write at 0"
            results[idx] = np.asarray(logits[i], np.float32)

    def _launch_advance(self, geom, stride, rows, results) -> None:
        pool = self._pool(geom)
        live = []
        states = []
        for idx, sid, payload in rows:
            s = self.table.get(sid)
            if s is None:  # evicted between classify and launch
                results[idx] = SessionUnknownError(
                    f"session {sid!r} evicted mid-launch; resend window")
                continue
            states.append(s)
            live.append((idx, sid, payload))
        if not live:
            return
        payload, bucket, scratch = self._stack(live, pool)
        slots = np.asarray([s.slot for s in states]
                           + [scratch] * (bucket - len(live)), np.int32)
        offs = np.asarray([s.off for s in states]
                          + [0] * (bucket - len(live)), np.int32)
        tpos = None
        if self._kv_meta is not None:
            # scratch rows get the just-established counter (T'), which
            # keeps their band/position arithmetic consistent with their
            # zero offsets
            tn = geom[0] // self._tok_meta["tt"]
            tpos = np.asarray([self._tpos_of(s) for s in states]
                              + [tn] * (bucket - len(live)), np.int32)
        fn = self._get_fn("advance", geom, stride, bucket)
        logits = self._guarded_call(fn, geom, pool, payload, slots, offs,
                                    tpos)
        for i, (idx, sid, _) in enumerate(live):
            self.table.advanced(sid, stride)
            results[idx] = np.asarray(logits[i], np.float32)

    def _guarded_call(self, fn, geom, pool, payload, slots, offs,
                      tpos=None):
        """`_call` with donated-buffer failure recovery: if the compiled
        step raises mid-execution (transient device OOM, XLA runtime
        error), the donated pool buffers are already deleted while the
        pool dict still references them — every later launch on this
        geometry would fail with 'array has been deleted' forever. Drop
        the pool and its sessions instead: clients re-establish from
        their resendable windows (the designed recovery path), and only
        THIS group's futures see the original error."""
        try:
            return self._call(fn, pool, payload, slots, offs, tpos)
        except Exception:
            dropped = self._invalidate_pool(geom)
            logger.exception(
                "stream: launch failed on %s; dropped the pool and its "
                "%d session(s) (donated ring buffers are gone — clients "
                "re-establish from their resendable windows)", geom,
                dropped)
            raise

    def _invalidate_pool(self, geom) -> int:
        """Forget a pool whose device buffers are lost; ends every
        session leased on it (their slots return to the free list, so a
        fresh pool of the same geometry starts clean). Returns the
        number of sessions dropped."""
        with self._lock:
            pool = self._pools.pop(geom, None)
            if pool is not None:
                self._committed -= pool["bytes"]
        if pool is not None:
            obs_memory.release(
                self._mem_component,
                pool.get("measured_bytes", pool["bytes"]),
                declared=pool["bytes"])
        dropped = 0
        for s in self.table.sessions():
            if s.pool_key == geom and self.table.end(s.sid):
                dropped += 1
        return dropped

    def _call(self, fn, pool, payload, slots, offs, tpos=None):
        """Run one compiled stream step, threading the donated ring
        pool(s) through in `_ring_names` order and committing the
        returned buffers."""
        eng = self.engine
        payload = self._replicated(payload)
        slots = self._replicated(slots)
        args = [eng.params, eng.batch_stats]
        args += [pool[nm] for nm in self._ring_names]
        args.append(payload)
        args.append(slots)
        if offs is not None:
            args.append(self._replicated(offs))
        if tpos is not None:
            args.append(self._replicated(tpos))
        out = fn(*args)
        for nm, buf in zip(self._ring_names, out):
            pool[nm] = buf
        return out[-1]

    def end_session(self, sid: str) -> bool:
        return self.table.end(sid)

    def warmup_stream(self, window: int, h: int, w: int, c: int,
                      stride: int) -> int:
        """Pre-compile establish+advance at EVERY bucket for one stream
        geometry (the cold-start analog of `InferenceEngine.warmup`, and
        what `prewarm_from` does for a hot-swap): scratch-slot launches,
        so no session is created and no ring is disturbed. Without this,
        the first lone-session arrival at each bucket size pays a
        synchronous compile on the scheduler's flush thread."""
        geom = self.geom_key(window, h, w, c, self.input_dtype)
        self._validate(geom, stride)
        pool = self._pool(geom)
        t, _, _, _, dtype = geom
        scratch = pool["cap"]
        n = 0
        for b in self.buckets:
            slots = np.full((b,), scratch, np.int32)
            fn = self._get_fn("establish", geom, stride, b)
            self._guarded_call(fn, geom, pool,
                               np.zeros((b, t, h, w, c), _np_dtype(dtype)),
                               slots, None)
            fn = self._get_fn("advance", geom, stride, b)
            tpos = None
            if self._kv_meta is not None:
                tpos = np.full((b,), t // self._tok_meta["tt"], np.int32)
            self._guarded_call(fn, geom, pool,
                               np.zeros((b, stride, h, w, c),
                                        _np_dtype(dtype)),
                               slots, np.zeros((b,), np.int32), tpos)
            n += 2
        return n

    # --- parity + probes --------------------------------------------------

    def full_recompute(self, windows: np.ndarray) -> np.ndarray:
        """The baseline the parity gate compares against: assemble the
        host windows (B, T, H, W, C), pad to the engine bucket, and run
        the ordinary one-shot `predict` — full H2D + full embed + trunk.
        For the dual-rate family the slow pathway is the phase-0
        subsample of the window (the slide-stable serving convention the
        slow ring implements)."""
        n = windows.shape[0]
        bucket = self.bucket_for(n)
        if bucket > n:
            pad = np.zeros((bucket - n,) + windows.shape[1:], windows.dtype)
            windows = np.concatenate([windows, pad], axis=0)
        if self.kind == "dual":
            alpha = self._tok_meta["alpha"]
            return self.engine.predict(
                {"slow": windows[:, ::alpha], "fast": windows})[:n]
        return self.engine.predict({"video": windows})[:n]

    def full_recompute_history(self, histories: np.ndarray,
                               window: int) -> np.ndarray:
        """The parity oracle for the STATEFUL families: recompute what
        the incremental path SHOULD produce from the entire per-session
        frame history since establish (B, F, H, W, C), F >= window.

        - KV trunks: one masked forward over the whole history with the
          band on absolute slot indices and ring-slot-stable positions —
          the cached-state semantics exactly (the last-window one-shot
          recompute is NOT equivalent: cached K/V legitimately attended
          context that has since left the ring).
        - stem ring: the full-history stem conv (real halo everywhere
          the stream had one), last T' stem slots through the trunk —
          where one-shot `predict` zero-pads the window edge.
        - exact-window families (frames / tokens-full / dual): delegates
          to `full_recompute` over the trailing window.

        Jitted per (kind, geometry-ish, shape) under the same `_fns`
        cache (each distinct history length is its own key, so the
        flat-cache probe stays honest)."""
        import jax.numpy as jnp

        histories = np.asarray(histories, _np_dtype(self.input_dtype))
        stateful_kv = self.kind == "tokens" and self.trunk != "full"
        if not (stateful_kv or self.kind == "stem"):
            return np.asarray(
                self.full_recompute(histories[:, -window:]), np.float32)
        key = ("replay", self.kind, int(window),
               tuple(int(s) for s in histories.shape))
        fn = self._fns.get(key)
        if fn is None:
            import jax

            t = int(window)
            if stateful_kv:
                m = self._tok_meta
                tn = t // m["tt"]
                fn_geom = self.geom_key(t, histories.shape[2],
                                        histories.shape[3],
                                        histories.shape[4],
                                        self.input_dtype)
                band = self._band_width(fn_geom)

                def replay(params, hist):
                    if self.quantization == "int8":
                        from pytorchvideo_accelerate_tpu.serving.quantize import (  # noqa: E501
                            dequantize_tree,
                        )

                        params = dequantize_tree(
                            params, self.engine._compute_dtype)
                    tok = self._embed_tokens(params, hist)  # (B, F', hw, d)
                    fslots = tok.shape[1]
                    slot_idx = jnp.broadcast_to(
                        jnp.arange(fslots, dtype=jnp.int32) % tn,
                        (tok.shape[0], fslots))
                    _, hid = self._trunk_kv_full(params, tok, slot_idx,
                                                 band, tn)
                    return self._head_logits(params,
                                             hid[:, -tn:].mean(axis=1))
            else:
                m = self._tok_meta
                tn = t // m["ts"]
                halo = m["halo"]

                def replay(params, hist):
                    if self.quantization == "int8":
                        from pytorchvideo_accelerate_tpu.serving.quantize import (  # noqa: E501
                            dequantize_tree,
                        )

                        params = dequantize_tree(
                            params, self.engine._compute_dtype)
                    stem = self._stem_embed(params, hist, (halo, halo))
                    return self._forward_stem(
                        params, self.engine.batch_stats, stem[:, -tn:])

            with self._lock:
                fn = self._fns.get(key)
                if fn is None:
                    fn = jax.jit(replay)
                    self._fns[key] = fn
        out = fn(self.engine.params, self._replicated(histories))
        return np.asarray(out, np.float32)

    def compiled_stream_keys(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._fns, key=repr))

    def compiled_stream_cache_sizes(self) -> Dict[tuple, Optional[int]]:
        """Per-compiled-function jit cache sizes — the RecompileGuard
        probe: steady-state streaming must keep every entry at 1."""
        from pytorchvideo_accelerate_tpu.analysis.recompile_guard import (
            cache_size,
        )

        with self._lock:
            return {k: cache_size(fn) for k, fn in self._fns.items()}

    # --- hot-swap state carry ---------------------------------------------

    def carry_state_from(self, blue: "StreamingEngine") -> int:
        """Cutover-time state carry (`Scheduler.swap_engine` calls this
        UNDER the launch lock, fleet/hotswap.py): adopt the blue engine's
        session table and RAW-family ring pools (raw/slow frames are
        weight-independent), then re-derive every weight-DERIVED ring
        (tok / kv / hid / stem) under THIS engine's weights — cached
        activations must never outlive the weights that produced them.
        The KV/stem rebuild runs the masked trunk over each adopted raw
        ring with per-row offsets and position counters from the adopted
        table (fresh-establish semantics: the rebuilt state carries the
        current window's context only). Returns the number of carried
        sessions.

        Why cutover and not prewarm: blue keeps LAUNCHING during prewarm,
        and every blue stream advance DONATES its pool buffer — a pool
        adopted early would be a deleted jax array by the time green
        serves it (and sessions established after an early carry would be
        silently lost). Under the launch lock blue is quiesced, so the
        adopt is race-free; `prepare_carry_from` pre-compiles the
        re-derive + stream steps at prewarm time so the only cutover cost
        is bounded execution (measured in swap_blackout_ms, honestly)."""
        from pytorchvideo_accelerate_tpu.obs import trace

        # traced: the carry is the session-state handoff between engines
        # (the swap-timeline hop the trace-propagation rule guards)
        with trace.span("stream_state_carry", engine=self.name):
            self.table.adopt(blue.table)
            carried = len(self.table.sessions())
            with blue._lock:
                blue_pools = dict(blue._pools)
            # re-derive OUTSIDE self._lock: the compiled helpers take the
            # same non-reentrant lock on a compile-cache miss (a geometry
            # blue grew mid-prewarm), and the scheduler's launch lock
            # already serializes this whole carry against launches
            adopted = {}
            for geom, pool in blue_pools.items():
                adopted[geom] = self._derive_rings(geom, pool)
            with self._lock:
                for geom, mine in adopted.items():
                    mine["measured_bytes"] = sum(
                        int(getattr(mine[nm], "nbytes", 0))
                        for nm in self._ring_names if nm in mine)
                    prior = self._pools.pop(geom, None)
                    if prior is not None:
                        self._committed -= prior["bytes"]
                        obs_memory.release(
                            self._mem_component,
                            prior.get("measured_bytes", prior["bytes"]),
                            declared=prior["bytes"])
                    self._pools[geom] = mine
                    self._committed += mine["bytes"]
                    obs_memory.register(self._mem_component,
                                        mine["measured_bytes"],
                                        declared=mine["bytes"])
        # the adopted raw rings (and blue's freed derived rings) now
        # belong to THIS engine's ledger component; blue retires
        obs_memory.release(blue._mem_component)
        logger.info("stream: carried %d session(s), %d pool(s) across "
                    "hot-swap", carried, len(blue_pools))
        return carried

    def _derive_rings(self, geom, blue_pool) -> Dict[str, Any]:
        """Build THIS engine's ring dict for one adopted blue pool. Bytes
        are re-accounted under this engine's own `ring_bytes` (a
        trunk-mode mismatch across the swap changes the ring family —
        carry preserves sessions first; the budget honest-counts the new
        footprint)."""
        raw = blue_pool["raw"]
        rows = raw.shape[0]
        mine: Dict[str, Any] = {
            "cap": blue_pool["cap"],
            "bytes": rows * max(self.ring_bytes(geom), 1),
            "raw": raw,
        }
        if self.kind == "dual":
            # both rings are raw frames — weight-independent; a blue
            # without a slow ring (cross-family swap) gets one rebuilt
            # from the raw ring's phase-0 subsample
            slow = blue_pool.get("slow")
            if slow is None:
                slow = raw[:, ::self._tok_meta["alpha"]]
            mine["slow"] = slow
        elif self.kind == "tokens":
            mine["tok"] = self._reembed_pool(geom, raw)
            if self.trunk != "full":
                offs, tpos = self._pool_positions(geom, rows)
                derived = self._rebuild_fn(geom, rows)(
                    self.engine.params, raw, self._replicated(offs),
                    self._replicated(tpos))
                for nm, buf in zip(("kv", "kv_scale", "hid")
                                   if "kv_scale" in self._ring_names
                                   else ("kv", "hid"), derived):
                    mine[nm] = buf
        elif self.kind == "stem":
            offs, _ = self._pool_positions(geom, rows)
            mine["stem"] = self._rebuild_stem_fn(geom, rows)(
                self.engine.params, raw, self._replicated(offs))
        return mine

    def _pool_positions(self, geom, rows: int):
        """Per-pool-row (off, tpos) host arrays from the (already
        adopted) session table — rows without a live session get the
        just-established values (off 0, tpos T'), keeping their scratch
        content well-formed."""
        gran = self._tok_meta["tt"] if self.kind == "tokens" \
            else self._tok_meta["ts"]
        tn = geom[0] // gran
        offs = np.zeros((rows,), np.int32)
        tpos = np.full((rows,), tn, np.int32)
        for s in self.table.sessions():
            if s.pool_key == geom and s.slot < rows:
                offs[s.slot] = s.off
                tpos[s.slot] = (s.window + s.frames_seen) // gran
        return offs, tpos

    def _reembed_fn(self, rows: int):
        """Jitted whole-pool re-embed, cached per row count (compiled at
        `prepare_carry_from` so the cutover-time carry only executes)."""
        import jax

        key = ("reembed", rows)
        fn = self._fns.get(key)
        if fn is None:
            with self._lock:
                fn = self._fns.get(key)
                if fn is None:
                    def reembed(params, frames):
                        if self.quantization == "int8":
                            from pytorchvideo_accelerate_tpu.serving.quantize import (  # noqa: E501
                                dequantize_tree,
                            )

                            params = dequantize_tree(
                                params, self.engine._compute_dtype)
                        return self._embed_tokens(params, frames)

                    fn = jax.jit(reembed)
                    self._fns[key] = fn
        return fn

    def _reembed_pool(self, geom, raw):
        """Re-embed a whole raw pool ((rows, T, H, W, C)) into a token
        pool under this engine's params — one jitted batch (compiled in
        advance by `prepare_carry_from`)."""
        m = self._tok_meta
        tok = self._reembed_fn(raw.shape[0])(self.engine.params, raw)
        expect = (raw.shape[0], geom[0] // m["tt"],
                  (geom[1] // m["p"]) * (geom[2] // m["p"]), m["dim"])
        assert tuple(tok.shape) == expect, (tok.shape, expect)
        return tok

    def _rebuild_fn(self, geom, rows: int):
        """Jitted whole-pool KV/hidden rebuild under THIS engine's
        weights, cached per (geom, rows): re-embed every raw ring, roll
        each row to logical (oldest-first) order by its token offset,
        run the masked trunk with ring-slot-stable positions recovered
        from the per-row position counter (`tpos % T' == off//tt`), and
        roll the per-layer K/V + hidden results back to ring order."""
        import jax

        key = ("rebuild", geom, rows)
        fn = self._fns.get(key)
        if fn is not None:
            return fn

        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.serving.quantize import (
            dequantize_tree,
            quantize_kv,
        )

        m = self._tok_meta
        tt = m["tt"]
        tn = geom[0] // tt
        window = self._band_width(geom)
        int8 = "kv_scale" in self._ring_names

        def rebuild(params, raw, offs, tpos):
            if self.quantization == "int8":
                params = dequantize_tree(params,
                                         self.engine._compute_dtype)
            tok = self._embed_tokens(params, raw)     # ring order
            toffs = offs // tt
            tok_l = jax.vmap(lambda r, o: jnp.roll(r, -o, axis=0))(
                tok, toffs)
            slot_idx = (tpos[:, None]
                        + jnp.arange(tn, dtype=jnp.int32)[None, :]) % tn
            kv_l, hid_l = self._trunk_kv_full(params, tok_l, slot_idx,
                                              window, tn)
            kv_r = jax.vmap(lambda r, o: jnp.roll(r, o, axis=2))(
                kv_l, toffs)
            hid_r = jax.vmap(lambda r, o: jnp.roll(r, o, axis=0))(
                hid_l, toffs)
            if int8:
                q8, sc = quantize_kv(kv_r)
                return tok, q8, sc, hid_r
            return tok, kv_r, hid_r

        with self._lock:
            fn2 = self._fns.get(key)
            if fn2 is None:
                fn2 = jax.jit(lambda p, r, o, t:
                              rebuild(p, r, o, t)[1:])
                # tok rides the dedicated reembed fn; the rebuild returns
                # only the KV-family rings — but both share the embed
                # subgraph, so re-deriving tok separately costs one more
                # CubeEmbed pass at cutover (bounded, measured in
                # swap_blackout_ms)
                self._fns[key] = fn2
            fn = fn2
        return fn

    def _rebuild_stem_fn(self, geom, rows: int):
        """Jitted whole-pool stem rebuild under THIS engine's weights,
        cached per (geom, rows): roll each raw ring to logical order,
        run the model-padded stem conv (fresh-establish semantics — the
        oldest slot's halo is the stream edge), roll back to ring
        order."""
        import jax

        key = ("rebuild_stem", geom, rows)
        fn = self._fns.get(key)
        if fn is not None:
            return fn

        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.serving.quantize import (
            dequantize_tree,
        )

        m = self._tok_meta
        ts, halo = m["ts"], m["halo"]

        def rebuild(params, raw, offs):
            if self.quantization == "int8":
                params = dequantize_tree(params,
                                         self.engine._compute_dtype)
            raw_l = jax.vmap(lambda r, o: jnp.roll(r, -o, axis=0))(
                raw, offs)
            stem_l = self._stem_embed(params, raw_l, (halo, halo))
            return jax.vmap(lambda r, o: jnp.roll(r, o, axis=0))(
                stem_l, offs // ts)

        with self._lock:
            fn2 = self._fns.get(key)
            if fn2 is None:
                fn2 = jax.jit(rebuild)
                self._fns[key] = fn2
            fn = fn2
        return fn

    def prepare_carry_from(self, blue: "StreamingEngine") -> int:
        """Prewarm half of the state carry (fleet/hotswap.prewarm_like):
        COMPILE every stream step the blue engine serves plus the
        whole-pool re-derives, by executing scratch/dummy calls — jax.jit
        is lazy, so merely constructing the wrappers would leave the
        first post-swap advance to compile on the flush thread (the cold
        start `warmup_stream` exists to prevent). Touches no blue
        buffer: blue keeps launching (and donating) during prewarm."""
        n = 0
        seen = set()
        for key in blue.compiled_stream_keys():
            if key[0] not in ("establish", "advance"):
                continue
            _, _, geom, stride, _ = key
            if (geom, stride) in seen:
                continue
            seen.add((geom, stride))
            t, h, w, c, _ = geom
            n += self.warmup_stream(t, h, w, c, stride)
        if self.kind in ("tokens", "stem"):
            with blue._lock:
                shapes = {g: p["raw"].shape for g, p in blue._pools.items()}
            for geom, shape in shapes.items():
                dummy = self._replicated(
                    np.zeros(shape, _np_dtype(geom[4])))
                rows = shape[0]
                if self.kind == "tokens":
                    self._reembed_pool(geom, dummy)
                    if self.trunk != "full":
                        zero = self._replicated(
                            np.zeros((rows,), np.int32))
                        tn = self._replicated(np.full(
                            (rows,), geom[0] // self._tok_meta["tt"],
                            np.int32))
                        self._rebuild_fn(geom, rows)(
                            self.engine.params, dummy, zero, tn)
                else:
                    zero = self._replicated(np.zeros((rows,), np.int32))
                    self._rebuild_stem_fn(geom, rows)(
                        self.engine.params, dummy, zero)
                n += 1
        return n

    def snapshot(self) -> Dict[str, float]:
        snap = self.table.snapshot()
        with self._lock:
            snap["stream_compiled"] = float(len(self._fns))
            snap["stream_pools"] = float(len(self._pools))
        return snap
