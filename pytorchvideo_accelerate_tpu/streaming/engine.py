"""StreamingEngine: device-resident rolling-window rings + incremental
advance steps, compiled once per (bucket, stride, geometry).

The recompute this eliminates (docs/SERVING.md § streaming): one-shot
clip classification re-ships and re-embeds the whole ``(T, H, W, C)``
window per emitted label, so a live stream scored at stride *s* pays
``T/s``x redundant H2D and patch-embed work. Here a session's window
lives ON DEVICE in a slot of a pre-allocated ring pool; an advance ships
only the *s* new frames, writes them into the ring in place (jitted,
pool donated — graphcheck-style zero double-buffering), and re-scores
the cached window.

Two ring families, chosen by the served model:

- **frame ring** (conv families — tiny3d/x3d/resnet/csn/r2plus1d/c2d,
  and any model without a token seam): the ring holds raw frames in the
  engine's input dtype; the advance saves H2D + host staging and the
  full trunk re-runs over the cached window (3-D convs mix time
  globally — there is no exact partial re-use seam).
- **token ring** (`VideoMAEClassifier`): the cube embedding is a VALID
  conv with kernel == stride, so each tubelet's token depends only on
  its own pixels — the ring caches PRE-positional patch tokens per
  temporal slot, the advance embeds just the new frames, and the trunk
  runs over cached tokens (positional embeddings are added at trunk
  time in window order, so the rotating ring start is invisible to the
  model). A raw-frame ring is kept alongside as the weight-independent
  carry substrate: across a blue/green hot-swap the green engine
  re-embeds every live ring from raw frames under ITS weights at
  cutover (`carry_state_from`, compiled in advance by
  `prepare_carry_from`), so cached tokens can never go stale against
  swapped weights. MViT's overlapping patch stem
  ((3,7,7) kernel, stride (2,4,4)) has no per-frame token independence
  and rides the frame ring.

Parity contract: the incremental logits match `InferenceEngine.predict`
over the assembled host window (`full_recompute`) — gated in the bench
STREAM lane and tests/test_zstream.py. SlowFast's dual-rate window pair
is refused loudly (two coupled rings at different strides — not built).

Compile discipline: advance/establish functions are jitted per
(kind, geometry, stride, bucket) and cached forever; session slots and
write offsets are TRACED arguments, so steady-state streaming touches
zero new executables (`compiled_stream_cache_sizes` is the
RecompileGuard-style probe the bench lane asserts flat).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from pytorchvideo_accelerate_tpu.streaming.session import (
    SessionAdmissionError,
    SessionError,
    SessionTable,
    SessionUnknownError,
)
from pytorchvideo_accelerate_tpu.utils.logging import get_logger
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

logger = get_logger("pva_tpu")

# compiled stream-executable bound, same rationale as the engine's
# MAX_COMPILED_KEYS: every (geometry, stride, bucket) costs a synchronous
# compile + permanent executable memory
MAX_STREAM_KEYS = 64


def _np_dtype(name: str):
    return np.dtype(name)


@shared_state("_pools", "_fns", "_committed", benign={
    "_tok_meta": "written once at construction, read-only afterwards"})
class StreamingEngine:
    """Session-stateful wrapper around one `InferenceEngine`.

    Presents the engine surface the scheduler/hot-swap stack already
    speaks (`predict`/`buckets`/`warmup`/`compiled_keys` delegate to the
    wrapped engine) plus the session surface (`advance_batch`,
    `end_session`, `carry_state_from`). `supports_sessions` is the
    capability flag the scheduler/server check before routing session
    traffic."""

    supports_sessions = True

    def __init__(self, engine, *, session_budget_mb: float = 256.0,
                 session_ttl_s: float = 120.0, retry_after_s: float = 1.0,
                 registry=None, name: str = "stream"):
        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.models import VideoMAEClassifier

        self.engine = engine
        self.name = name
        self.session_budget_bytes = int(session_budget_mb * 1e6)
        self.table = SessionTable(ttl_s=session_ttl_s,
                                  retry_after_s=retry_after_s,
                                  registry=registry, name=name)
        self._lock = make_lock("StreamingEngine._lock")
        # pool_key -> {"raw": device (cap,T,H,W,C), "tok": device or None}
        self._pools: Dict[tuple, Dict[str, Any]] = {}
        self._committed = 0  # ring-pool bytes allocated against the budget
        self._fns: Dict[tuple, Any] = {}  # (op, kind, geom, stride, bucket)
        model = engine.model
        if isinstance(model, VideoMAEClassifier):
            self.kind = "tokens"
            tt, p, _ = model.tubelet
            self._tok_meta = {"tt": int(tt), "p": int(p),
                              "dim": int(model.dim),
                              "dtype": model.dtype}
        else:
            self.kind = "frames"
            self._tok_meta = None
        if getattr(model, "__class__", type(None)).__name__ == "SlowFast" \
                or engine.model_name.startswith("slowfast"):
            raise SessionError(
                "streaming sessions are single-clip ('video') families; "
                "slowfast's dual-rate (slow, fast) window pair needs two "
                "coupled rings at different strides and is not supported "
                "(docs/SERVING.md § streaming)")
        self._jnp = jnp

    # --- delegated engine surface ----------------------------------------

    @property
    def buckets(self):
        return self.engine.buckets

    @property
    def mesh(self):
        return self.engine.mesh

    @property
    def model(self):
        return self.engine.model

    @property
    def model_name(self):
        return self.engine.model_name

    @property
    def num_classes(self):
        return self.engine.num_classes

    @property
    def input_dtype(self):
        return self.engine.input_dtype

    @property
    def stats(self):
        return self.engine.stats

    @property
    def quantization(self):
        return getattr(self.engine, "quantization", "off")

    @property
    def compiled_keys(self):
        return self.engine.compiled_keys

    def bucket_for(self, n: int) -> int:
        return self.engine.bucket_for(n)

    def predict(self, batch):
        return self.engine.predict(batch)

    def warmup(self, sample_clip) -> None:
        self.engine.warmup(sample_clip)

    # --- geometry ---------------------------------------------------------

    @staticmethod
    def geom_key(window: int, h: int, w: int, c: int, dtype: str) -> tuple:
        return (int(window), int(h), int(w), int(c), str(dtype))

    def ring_bytes(self, geom: tuple) -> int:
        """Device bytes ONE session's ring(s) cost — the unit of the HBM
        session budget."""
        t, h, w, c, dtype = geom
        raw = t * h * w * c * _np_dtype(dtype).itemsize
        if self.kind == "tokens":
            m = self._tok_meta
            tok_itemsize = np.dtype(
                self._jnp.zeros((), m["dtype"]).dtype).itemsize
            raw += (t // m["tt"]) * (h // m["p"]) * (w // m["p"]) \
                * m["dim"] * tok_itemsize
        return raw

    def advance_h2d_bytes(self, geom: tuple, stride: int) -> int:
        """Host->device payload bytes per incremental advance (exact)."""
        _, h, w, c, dtype = geom
        return stride * h * w * c * _np_dtype(dtype).itemsize

    def full_h2d_bytes(self, geom: tuple) -> int:
        """Host->device payload bytes per full-window recompute (exact)."""
        t, h, w, c, dtype = geom
        return t * h * w * c * _np_dtype(dtype).itemsize

    def _validate(self, geom: tuple, stride: int) -> None:
        t, h, w, c, _ = geom
        if stride <= 0 or t % stride != 0:
            raise SessionError(
                f"stride {stride} must divide the window length {t} "
                "(ring writes must never wrap mid-advance)")
        if self.kind == "tokens":
            m = self._tok_meta
            if stride % m["tt"] != 0:
                raise SessionError(
                    f"stride {stride} must be a multiple of the model's "
                    f"temporal tubelet {m['tt']} (token-ring granularity)")
            if t % m["tt"] or h % m["p"] or w % m["p"]:
                raise SessionError(
                    f"window geometry {(t, h, w)} does not tile the "
                    f"tubelet {(m['tt'], m['p'], m['p'])}")

    # --- pools ------------------------------------------------------------

    def _pool(self, geom: tuple) -> Dict[str, Any]:
        """Get-or-create the ring pool for `geom` (replicated over the
        engine's mesh — per-replica single-device meshes are the fleet
        pattern, so replication is free there; a multi-device serving
        mesh pays HBM for simplicity, documented).

        The session budget is GLOBAL across pools: each new geometry's
        pool is sized from the budget's REMAINING bytes (first geometry
        gets most of it), and a geometry whose pool would hold zero
        sessions is refused — a client fanning out novel window shapes
        must exhaust the budget into 503s, never allocate
        budget-per-shape until the device OOMs."""
        with self._lock:
            pool = self._pools.get(geom)
            if pool is not None:
                return pool
            ring = max(self.ring_bytes(geom), 1)
            remaining = self.session_budget_bytes - self._committed
            cap = remaining // ring
            if cap < 1:
                raise SessionAdmissionError(
                    f"session budget exhausted ({self.name}: "
                    f"{self._committed / 1e6:.0f} MB committed of "
                    f"{self.session_budget_bytes / 1e6:.0f} MB; a "
                    f"{ring / 1e6:.1f} MB/session pool for {geom} does "
                    "not fit); retry later",
                    retry_after_s=self.table.retry_after_s)
            # +1 scratch slot: padded launch rows write here, never into a
            # leased ring
            pool = {"raw": self._alloc_raw(geom, int(cap) + 1),
                    "tok": (self._alloc_tok(geom, int(cap) + 1)
                            if self.kind == "tokens" else None),
                    "cap": int(cap),
                    "bytes": int(cap + 1) * ring}
            self._pools[geom] = pool
            self._committed += pool["bytes"]
            self.table.register_pool(geom, int(cap))
            logger.info(
                "stream: pool %s = %d session slots (+1 scratch), "
                "%.1f MB/session; %.0f/%.0f MB budget committed",
                geom, cap, ring / 1e6, self._committed / 1e6,
                self.session_budget_bytes / 1e6)
            return pool

    def _replicated(self, arr):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr, NamedSharding(self.mesh, P()))

    def _alloc_raw(self, geom: tuple, rows: int):
        t, h, w, c, dtype = geom
        return self._replicated(np.zeros((rows, t, h, w, c),
                                         _np_dtype(dtype)))

    def _alloc_tok(self, geom: tuple, rows: int):
        t, h, w, c, _ = geom
        m = self._tok_meta
        return self._replicated(np.zeros(
            (rows, t // m["tt"], (h // m["p"]) * (w // m["p"]), m["dim"]),
            self._jnp.zeros((), m["dtype"]).dtype))

    # --- compiled steps ---------------------------------------------------

    def _forward_windows(self, params, bstats, windows):
        """The wrapped engine's exact forward over in-graph windows
        (B, T, H, W, C): constrain -> normalize -> model — the op sequence
        of `InferenceEngine._make_forward`, so incremental logits carry
        serving parity by construction."""
        import jax.numpy as jnp

        from pytorchvideo_accelerate_tpu.serving.quantize import (
            dequantize_tree,
        )
        from pytorchvideo_accelerate_tpu.trainer.steps import (
            _constrain_batch,
            device_normalize_batch,
            model_inputs,
            multiview_logits,
        )

        eng = self.engine
        if self.quantization == "int8":
            params = dequantize_tree(params, eng._compute_dtype)
        batch = _constrain_batch({"video": windows}, eng.mesh,
                                 leading_micro=False)
        batch = device_normalize_batch(batch, eng._device_normalize)
        logits = multiview_logits(
            lambda x: eng.model.apply(
                {"params": params, "batch_stats": bstats}, x, train=False),
            model_inputs(batch))
        return logits.astype(jnp.float32)

    def _embed_tokens(self, params, frames):
        """Patch-embed (B, t, H, W, C) frames -> (B, t/tt, hw, dim)
        pre-positional tokens: normalize (u8 engines) then the
        classifier's own CubeEmbed applied from its param subtree — each
        tubelet's token is a pure function of its own pixels, which is
        the whole reason the token ring is exact. `params` must already
        be dequantized (the compiled step dequantizes once at its top)."""
        from pytorchvideo_accelerate_tpu.models.videomae import CubeEmbed
        from pytorchvideo_accelerate_tpu.trainer.steps import (
            device_normalize_batch,
        )

        m = self._tok_meta
        model = self.engine.model
        x = device_normalize_batch({"video": frames},
                                   self.engine._device_normalize)["video"]
        tokens, (t, h, w) = CubeEmbed(
            model.dim, model.tubelet, model.dtype, name="patch_embed",
        ).apply({"params": params["encoder"]["patch_embed"]}, x)
        return tokens.reshape(tokens.shape[0], t, h * w, m["dim"])

    def _forward_tokens(self, params, tok_windows):
        """Trunk from cached tokens: + window-order positional embedding
        -> ViT blocks -> mean-pool -> fc_norm -> head, mirroring
        `VideoMAEClassifier.__call__` op for op (final_norm=False,
        deterministic dropout). `params` arrive dequantized."""
        import jax.numpy as jnp
        from flax import linen as nn

        from pytorchvideo_accelerate_tpu.models.videomae import (
            ViTBlock,
            sincos_pos_embed,
        )
        from pytorchvideo_accelerate_tpu.parallel.sharding import (
            constrain_block,
        )
        from pytorchvideo_accelerate_tpu.precision import f32_island

        model = self.engine.model
        b, t, hw, dim = tok_windows.shape
        tokens = tok_windows.reshape(b, t * hw, dim)
        pos = jnp.asarray(sincos_pos_embed(t * hw, dim))[None]
        tokens = tokens + pos.astype(tokens.dtype)
        for i in range(model.depth):
            tokens = ViTBlock(
                dim=model.dim, num_heads=model.num_heads,
                attention_backend=model.attention_backend,
                context_mesh=model.context_mesh, dtype=model.dtype,
            ).apply({"params": params["encoder"][f"block{i}"]}, tokens)
            tokens = constrain_block(tokens,
                                     getattr(model, "shard_mesh", None))
        feat = tokens.mean(axis=1)
        feat = nn.LayerNorm(dtype=model.dtype).apply(
            {"params": params["fc_norm"]}, feat)
        logits = nn.Dense(model.num_classes, dtype=jnp.float32).apply(
            {"params": params["head"]}, f32_island(feat))
        return logits.astype(jnp.float32)

    def _get_fn(self, op: str, geom: tuple, stride: int, bucket: int):
        key = (op, self.kind, geom, int(stride), int(bucket))
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                if len(self._fns) >= MAX_STREAM_KEYS:
                    raise SessionError(
                        f"engine already compiled {len(self._fns)} stream "
                        "geometries; refusing a new one (clients should "
                        "send the serving stream geometry)")
                fn = self._build_fn(op, geom, stride, bucket)
                self._fns[key] = fn
                logger.info("stream: compiling %s for %s stride=%d B=%d",
                            op, geom, stride, bucket)
        return fn

    def _build_fn(self, op: str, geom: tuple, stride: int, bucket: int):
        import jax
        import jax.numpy as jnp

        tokens = self.kind == "tokens"
        m = self._tok_meta

        def dq(params):
            # token-path dequant happens ONCE here: the embed and the
            # trunk both read the same fp view, and XLA fuses q*scale
            # into the weight reads exactly like the engine forward
            if tokens and self.quantization == "int8":
                from pytorchvideo_accelerate_tpu.serving.quantize import (
                    dequantize_tree,
                )

                return dequantize_tree(params, self.engine._compute_dtype)
            return params

        def write(pool, rows, slots, offs):
            """Write per-session rows into the donated pool at traced
            (slot, offset) — a sequential fori_loop of
            dynamic_update_slice, which XLA applies IN PLACE on the
            donated buffer: the update moves only the new rows' bytes,
            never whole rings (the gather-modify-scatter formulation
            copied every ring three times and cost more than the H2D it
            saved). Offsets never wrap because stride divides the
            window; scratch-slot duplicates are benign (sequential)."""
            def body(i, p):
                return jax.lax.dynamic_update_slice(
                    p, rows[i][None].astype(p.dtype),
                    (slots[i], offs[i]) + (0,) * (p.ndim - 2))

            return jax.lax.fori_loop(0, rows.shape[0], body, pool)

        if op == "advance" and not tokens:
            def fn(params, bstats, raw, frames, slots, offs):
                raw = write(raw, frames, slots, offs)
                windows = jax.vmap(
                    lambda r, o: jnp.roll(r, -(o + stride), axis=0)
                )(raw[slots], offs)
                return raw, self._forward_windows(params, bstats, windows)

            return jax.jit(fn, donate_argnums=(2,))

        if op == "advance" and tokens:
            tstride = stride // m["tt"]

            def fn(params, bstats, raw, tok, frames, slots, offs):
                params = dq(params)
                raw = write(raw, frames, slots, offs)
                new_tok = self._embed_tokens(params, frames)
                tok = write(tok, new_tok, slots, offs // m["tt"])
                tok_windows = jax.vmap(
                    lambda r, o: jnp.roll(r, -(o // m["tt"] + tstride),
                                          axis=0))(tok[slots], offs)
                return (raw, tok,
                        self._forward_tokens(params, tok_windows))

            return jax.jit(fn, donate_argnums=(2, 3))

        if op == "establish" and not tokens:
            def fn(params, bstats, raw, windows, slots):
                raw = write(raw, windows, slots, jnp.zeros_like(slots))
                # the freshly-written rings ARE the input windows (offset
                # 0): forward from the input, no gather-back needed
                return raw, self._forward_windows(
                    params, bstats, windows.astype(raw.dtype))

            return jax.jit(fn, donate_argnums=(2,))

        if op == "establish" and tokens:
            def fn(params, bstats, raw, tok, windows, slots):
                params = dq(params)
                zeros = jnp.zeros_like(slots)
                raw = write(raw, windows, slots, zeros)
                new_tok = self._embed_tokens(params, windows)
                tok = write(tok, new_tok, slots, zeros)
                return raw, tok, self._forward_tokens(params, new_tok)

            return jax.jit(fn, donate_argnums=(2, 3))

        raise SessionError(f"unknown stream op {op!r}")

    # --- the session surface ---------------------------------------------

    def advance_batch(self, items: List[dict]) -> List[Any]:
        """Score one launch of session advances. Each item:
        ``{"sid": str, "frames": (s, H, W, C), "window": optional
        (T, H, W, C) resendable window, "end": bool}``.

        Routing per item: a session this replica holds advances
        incrementally; an unknown/mismatched one re-establishes
        DETERMINISTICALLY from the item's resendable window (how replica
        death and affinity re-routes stay client-invisible) or fails
        with `SessionUnknownError` when no window rides along. Items are
        grouped into same-(geometry, stride) compiled launches; duplicate
        sids within one call are serialized into waves (a ring must never
        be read and written by two rows of one launch). Returns one entry
        PER ITEM in order: fp32 logits, or the Exception that item earned
        — a malformed item must fail ITS future, never its co-batched
        neighbours'."""
        self.table.sweep()
        results: List[Any] = [None] * len(items)
        pending = list(enumerate(items))
        while pending:
            wave: List[tuple] = []
            seen: set = set()
            rest: List[tuple] = []
            for idx, item in pending:
                sid = str(item.get("sid", ""))
                if sid in seen:
                    rest.append((idx, item))
                else:
                    seen.add(sid)
                    wave.append((idx, item))
            self._run_wave(wave, results)
            pending = rest
        for item in items:
            if item.get("end"):
                self.table.end(str(item.get("sid", "")))
        return results

    def _classify(self, item: dict) -> tuple:
        """-> (mode, sid, payload np, geom, stride) for one item; decides
        advance vs re-establish and validates against the session/ring
        contract."""
        sid = str(item.get("sid") or "")
        if not sid:
            raise SessionError("stream item carries no session id")
        frames = item.get("frames")
        window = item.get("window")
        if frames is None and window is None:
            raise SessionError(f"stream item for {sid!r} carries neither "
                               "frames nor a window")
        dtype = self.input_dtype
        if window is not None:
            window = np.asarray(window, dtype)
            if window.ndim != 4:
                raise SessionError(
                    f"window for {sid!r} must be (T, H, W, C), got "
                    f"{window.shape}")
        if frames is not None:
            frames = np.asarray(frames, dtype)
            if frames.ndim != 4:
                raise SessionError(
                    f"frames for {sid!r} must be (s, H, W, C), got "
                    f"{frames.shape}")
        state = self.table.get(sid)
        if state is not None and frames is not None:
            geom = state.pool_key
            if (frames.shape[0] == state.stride
                    and tuple(frames.shape[1:]) == tuple(geom[1:4])):
                return ("advance", sid, frames, geom, state.stride)
            # stride/geometry drift: fall through to re-establish (window
            # required — silently writing drifted frames would corrupt
            # the ring)
        if window is None:
            raise SessionUnknownError(
                f"session {sid!r} is not established on this replica and "
                "the request carries no resendable window")
        t, h, w, c = window.shape
        stride = int(item.get("stride") or
                     (frames.shape[0] if frames is not None else 0) or 0)
        if stride <= 0:
            raise SessionError(
                f"establish for {sid!r} needs a stride (frames payload or "
                "explicit 'stride')")
        geom = self.geom_key(t, h, w, c, dtype)
        self._validate(geom, stride)
        return ("establish", sid, window, geom, stride)

    def _run_wave(self, wave: List[tuple], results: List[Any]) -> None:
        """Group one duplicate-free wave by (mode, geom, stride) and run
        each group as one bucketed compiled launch. Per-item
        classification/admission failures land in `results` as
        exceptions; the rest of the wave still launches."""
        groups: Dict[tuple, List[tuple]] = {}
        for idx, item in wave:
            try:
                mode, sid, payload, geom, stride = self._classify(item)
            except Exception as e:  # noqa: BLE001 - per-item verdict
                results[idx] = e
                continue
            groups.setdefault((mode, geom, stride), []).append(
                (idx, sid, payload))
        for (mode, geom, stride), rows in groups.items():
            try:
                if mode == "establish":
                    self._launch_establish(geom, stride, rows, results)
                else:
                    self._launch_advance(geom, stride, rows, results)
            except Exception as e:  # noqa: BLE001 - contain to THIS group
                # a group-level failure (MAX_STREAM_KEYS refusal for a
                # novel geometry, a compile error) must fail the group
                # that caused it — never the other geometries co-batched
                # in the same flush
                for idx, _, _ in rows:
                    if results[idx] is None:
                        results[idx] = e

    def _stack(self, rows, pool) -> tuple:
        """Pad a group to its bucket: payload rows stacked with zero
        rows, slots padded with the pool's scratch row, offsets 0."""
        n = len(rows)
        bucket = self.bucket_for(n)
        payload = np.stack([p for _, _, p in rows])
        if bucket > n:
            pad = np.zeros((bucket - n,) + payload.shape[1:], payload.dtype)
            payload = np.concatenate([payload, pad], axis=0)
        return payload, bucket, pool["cap"]

    def _launch_establish(self, geom, stride, rows, results) -> None:
        pool = self._pool(geom)
        live = []
        states = []
        for idx, sid, payload in rows:
            try:
                # the admission decision (TTL eviction vs 503) happens
                # here, per session, against the HBM budget
                states.append(self.table.establish(
                    sid, geom, stride=stride, window=geom[0]))
                live.append((idx, sid, payload))
            except Exception as e:  # noqa: BLE001 - per-item verdict
                results[idx] = e
        if not live:
            return
        payload, bucket, scratch = self._stack(live, pool)
        slots = np.asarray([s.slot for s in states]
                           + [scratch] * (bucket - len(live)), np.int32)
        fn = self._get_fn("establish", geom, stride, bucket)
        logits = self._guarded_call(fn, geom, pool, payload, slots, None)
        for i, (idx, sid, _) in enumerate(live):
            # establish resets the write offset to 0; the committed
            # position is "window seen, next write at 0"
            results[idx] = np.asarray(logits[i], np.float32)

    def _launch_advance(self, geom, stride, rows, results) -> None:
        pool = self._pool(geom)
        live = []
        states = []
        for idx, sid, payload in rows:
            s = self.table.get(sid)
            if s is None:  # evicted between classify and launch
                results[idx] = SessionUnknownError(
                    f"session {sid!r} evicted mid-launch; resend window")
                continue
            states.append(s)
            live.append((idx, sid, payload))
        if not live:
            return
        payload, bucket, scratch = self._stack(live, pool)
        slots = np.asarray([s.slot for s in states]
                           + [scratch] * (bucket - len(live)), np.int32)
        offs = np.asarray([s.off for s in states]
                          + [0] * (bucket - len(live)), np.int32)
        fn = self._get_fn("advance", geom, stride, bucket)
        logits = self._guarded_call(fn, geom, pool, payload, slots, offs)
        for i, (idx, sid, _) in enumerate(live):
            self.table.advanced(sid, stride)
            results[idx] = np.asarray(logits[i], np.float32)

    def _guarded_call(self, fn, geom, pool, payload, slots, offs):
        """`_call` with donated-buffer failure recovery: if the compiled
        step raises mid-execution (transient device OOM, XLA runtime
        error), the donated pool buffers are already deleted while the
        pool dict still references them — every later launch on this
        geometry would fail with 'array has been deleted' forever. Drop
        the pool and its sessions instead: clients re-establish from
        their resendable windows (the designed recovery path), and only
        THIS group's futures see the original error."""
        try:
            return self._call(fn, pool, payload, slots, offs)
        except Exception:
            dropped = self._invalidate_pool(geom)
            logger.exception(
                "stream: launch failed on %s; dropped the pool and its "
                "%d session(s) (donated ring buffers are gone — clients "
                "re-establish from their resendable windows)", geom,
                dropped)
            raise

    def _invalidate_pool(self, geom) -> int:
        """Forget a pool whose device buffers are lost; ends every
        session leased on it (their slots return to the free list, so a
        fresh pool of the same geometry starts clean). Returns the
        number of sessions dropped."""
        with self._lock:
            pool = self._pools.pop(geom, None)
            if pool is not None:
                self._committed -= pool["bytes"]
        dropped = 0
        for s in self.table.sessions():
            if s.pool_key == geom and self.table.end(s.sid):
                dropped += 1
        return dropped

    def _call(self, fn, pool, payload, slots, offs):
        """Run one compiled stream step, threading the donated pool(s)
        through and committing the returned buffers."""
        eng = self.engine
        payload = self._replicated(payload)
        slots = self._replicated(slots)
        args = [eng.params, eng.batch_stats, pool["raw"]]
        if self.kind == "tokens":
            args.append(pool["tok"])
        args.append(payload)
        args.append(slots)
        if offs is not None:
            args.append(self._replicated(offs))
        out = fn(*args)
        if self.kind == "tokens":
            pool["raw"], pool["tok"], logits = out
        else:
            pool["raw"], logits = out
        return logits

    def end_session(self, sid: str) -> bool:
        return self.table.end(sid)

    def warmup_stream(self, window: int, h: int, w: int, c: int,
                      stride: int) -> int:
        """Pre-compile establish+advance at EVERY bucket for one stream
        geometry (the cold-start analog of `InferenceEngine.warmup`, and
        what `prewarm_from` does for a hot-swap): scratch-slot launches,
        so no session is created and no ring is disturbed. Without this,
        the first lone-session arrival at each bucket size pays a
        synchronous compile on the scheduler's flush thread."""
        geom = self.geom_key(window, h, w, c, self.input_dtype)
        self._validate(geom, stride)
        pool = self._pool(geom)
        t, _, _, _, dtype = geom
        scratch = pool["cap"]
        n = 0
        for b in self.buckets:
            slots = np.full((b,), scratch, np.int32)
            fn = self._get_fn("establish", geom, stride, b)
            self._guarded_call(fn, geom, pool,
                               np.zeros((b, t, h, w, c), _np_dtype(dtype)),
                               slots, None)
            fn = self._get_fn("advance", geom, stride, b)
            self._guarded_call(fn, geom, pool,
                               np.zeros((b, stride, h, w, c),
                                        _np_dtype(dtype)),
                               slots, np.zeros((b,), np.int32))
            n += 2
        return n

    # --- parity + probes --------------------------------------------------

    def full_recompute(self, windows: np.ndarray) -> np.ndarray:
        """The baseline the parity gate compares against: assemble the
        host windows (B, T, H, W, C), pad to the engine bucket, and run
        the ordinary one-shot `predict` — full H2D + full embed + trunk."""
        n = windows.shape[0]
        bucket = self.bucket_for(n)
        if bucket > n:
            pad = np.zeros((bucket - n,) + windows.shape[1:], windows.dtype)
            windows = np.concatenate([windows, pad], axis=0)
        return self.engine.predict({"video": windows})[:n]

    def compiled_stream_keys(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._fns))

    def compiled_stream_cache_sizes(self) -> Dict[tuple, Optional[int]]:
        """Per-compiled-function jit cache sizes — the RecompileGuard
        probe: steady-state streaming must keep every entry at 1."""
        from pytorchvideo_accelerate_tpu.analysis.recompile_guard import (
            cache_size,
        )

        with self._lock:
            return {k: cache_size(fn) for k, fn in self._fns.items()}

    # --- hot-swap state carry ---------------------------------------------

    def carry_state_from(self, blue: "StreamingEngine") -> int:
        """Cutover-time state carry (`Scheduler.swap_engine` calls this
        UNDER the launch lock, fleet/hotswap.py): adopt the blue engine's
        session table and RAW ring pools (raw frames are
        weight-independent), then re-derive every token pool under THIS
        engine's weights — cached embeddings must never outlive the
        weights that produced them. Returns the number of carried
        sessions.

        Why cutover and not prewarm: blue keeps LAUNCHING during prewarm,
        and every blue stream advance DONATES its pool buffer — a pool
        adopted early would be a deleted jax array by the time green
        serves it (and sessions established after an early carry would be
        silently lost). Under the launch lock blue is quiesced, so the
        adopt is race-free; `prepare_carry_from` pre-compiles the
        re-embed + stream steps at prewarm time so the only cutover cost
        is bounded execution (measured in swap_blackout_ms, honestly)."""
        from pytorchvideo_accelerate_tpu.obs import trace

        # traced: the carry is the session-state handoff between engines
        # (the swap-timeline hop the trace-propagation rule guards)
        with trace.span("stream_state_carry", engine=self.name):
            self.table.adopt(blue.table)
            carried = len(self.table.sessions())
            with blue._lock:
                blue_pools = dict(blue._pools)
            # re-embed OUTSIDE self._lock: _reembed_fn takes the same
            # non-reentrant lock on a compile-cache miss (a geometry blue
            # grew mid-prewarm), and the scheduler's launch lock already
            # serializes this whole carry against launches
            adopted = {}
            for geom, pool in blue_pools.items():
                mine = {"raw": pool["raw"], "tok": None,
                        "cap": pool["cap"], "bytes": pool["bytes"]}
                if self.kind == "tokens":
                    mine["tok"] = self._reembed_pool(geom, pool["raw"])
                adopted[geom] = mine
            with self._lock:
                for geom, mine in adopted.items():
                    prior = self._pools.pop(geom, None)
                    if prior is not None:
                        self._committed -= prior["bytes"]
                    self._pools[geom] = mine
                    self._committed += mine["bytes"]
        logger.info("stream: carried %d session(s), %d pool(s) across "
                    "hot-swap", carried, len(blue_pools))
        return carried

    def _reembed_fn(self, rows: int):
        """Jitted whole-pool re-embed, cached per row count (compiled at
        `prepare_carry_from` so the cutover-time carry only executes)."""
        import jax

        key = ("reembed", rows)
        fn = self._fns.get(key)
        if fn is None:
            with self._lock:
                fn = self._fns.get(key)
                if fn is None:
                    def reembed(params, frames):
                        if self.quantization == "int8":
                            from pytorchvideo_accelerate_tpu.serving.quantize import (  # noqa: E501
                                dequantize_tree,
                            )

                            params = dequantize_tree(
                                params, self.engine._compute_dtype)
                        return self._embed_tokens(params, frames)

                    fn = jax.jit(reembed)
                    self._fns[key] = fn
        return fn

    def _reembed_pool(self, geom, raw):
        """Re-embed a whole raw pool ((rows, T, H, W, C)) into a token
        pool under this engine's params — one jitted batch (compiled in
        advance by `prepare_carry_from`)."""
        m = self._tok_meta
        tok = self._reembed_fn(raw.shape[0])(self.engine.params, raw)
        expect = (raw.shape[0], geom[0] // m["tt"],
                  (geom[1] // m["p"]) * (geom[2] // m["p"]), m["dim"])
        assert tuple(tok.shape) == expect, (tok.shape, expect)
        return tok

    def prepare_carry_from(self, blue: "StreamingEngine") -> int:
        """Prewarm half of the state carry (fleet/hotswap.prewarm_like):
        COMPILE every stream step the blue engine serves plus the
        whole-pool re-embed, by executing scratch/dummy calls — jax.jit
        is lazy, so merely constructing the wrappers would leave the
        first post-swap advance to compile on the flush thread (the cold
        start `warmup_stream` exists to prevent). Touches no blue
        buffer: blue keeps launching (and donating) during prewarm."""
        n = 0
        seen = set()
        for key in blue.compiled_stream_keys():
            if key[0] not in ("establish", "advance"):
                continue
            _, _, geom, stride, _ = key
            if (geom, stride) in seen:
                continue
            seen.add((geom, stride))
            t, h, w, c, _ = geom
            n += self.warmup_stream(t, h, w, c, stride)
        if self.kind == "tokens":
            with blue._lock:
                shapes = {g: p["raw"].shape for g, p in blue._pools.items()}
            for geom, shape in shapes.items():
                dummy = self._replicated(
                    np.zeros(shape, _np_dtype(geom[4])))
                self._reembed_pool(geom, dummy)
                n += 1
        return n

    def snapshot(self) -> Dict[str, float]:
        snap = self.table.snapshot()
        with self._lock:
            snap["stream_compiled"] = float(len(self._fns))
            snap["stream_pools"] = float(len(self._pools))
        return snap
