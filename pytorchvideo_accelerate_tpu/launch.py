"""Local process launcher — the `accelerate launch` equivalent.

The reference wraps every run in `accelerate launch run.py ...`
(`/root/reference/run_slowfast_r50.sh:1`), which spawns N processes and
wires RANK/WORLD_SIZE/MASTER_ADDR before `init_process_group`
(accelerate/commands/launch.py:986-1030). The TPU-native equivalent is much
smaller because device collectives need no process-group bootstrap — XLA
compiles them from shardings — but multi-HOST runs still need one process
per host wired to a coordinator (`jax.distributed`). This launcher:

- spawns `--num_processes` local Python processes, each with the `PVA_*`
  env contract consumed by `parallel.distributed.initialize_distributed`
  (PVA_COORDINATOR_ADDRESS / PVA_NUM_PROCESSES / PVA_PROCESS_ID);
- picks a free coordinator port when none is given;
- streams rank-0 output through, prefixes other ranks' lines;
- tears the group down on the first failure and propagates the exit code.

On a real TPU pod the per-host process is normally started by the pod
scheduler and `initialize_distributed` self-configures; this launcher's
production role is single-host multi-process runs and — exactly like the
backbone's own test strategy (SURVEY §4.1: accelerate launches 2-process
CPU/gloo jobs in its test suite) — real multi-process integration tests on
CPU (tests/test_launch.py).

Usage:
    python -m pytorchvideo_accelerate_tpu.launch --num_processes 2 -- \
        --cpu --synthetic --optim.num_epochs 1 ...         # default module
    python -m pytorchvideo_accelerate_tpu.launch --num_processes 2 -- \
        my_script.py --my-flag                             # arbitrary script
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import time
import sys
import threading
from typing import List, Optional, Sequence

from pytorchvideo_accelerate_tpu.utils.sync import make_thread


def find_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _forward(stream, rank: int) -> None:
    for line in iter(stream.readline, b""):
        sys.stderr.buffer.write(f"[rank {rank}] ".encode() + line)
        sys.stderr.buffer.flush()
    stream.close()


def build_commands(num_processes: int, prog: List[str]) -> List[List[str]]:
    if prog and prog[0].endswith(".py"):
        base = [sys.executable, *prog]
    else:
        base = [sys.executable, "-m", "pytorchvideo_accelerate_tpu.run", *prog]
    return [list(base) for _ in range(num_processes)]


def launch(num_processes: int, prog: List[str],
           coordinator_address: str = "", env_extra: Optional[dict] = None,
           timeout: Optional[float] = None, max_restarts: int = 0) -> int:
    """Spawn the process group; returns the first non-zero exit code or 0.

    `max_restarts`: torchelastic-style supervision (the reference launch
    path's `torch.distributed.run` restart-on-failure semantics,
    accelerate/commands/launch.py:999,1023): on any rank failure the whole
    group is torn down and relaunched, up to `max_restarts` times. Pair with
    `--resume_from_checkpoint auto` so relaunched training continues from
    the latest checkpoint. `timeout` applies per attempt.
    """
    if num_processes < 1:
        raise ValueError(f"--num_processes must be >= 1, got {num_processes}")
    if max_restarts < 0:
        raise ValueError(f"--max_restarts must be >= 0, got {max_restarts}")
    for attempt in range(max_restarts + 1):
        # fresh coordinator port per attempt unless pinned: the previous
        # attempt's dying coordinator may still hold the old one
        addr = coordinator_address or f"127.0.0.1:{find_free_port()}"
        rc = _run_group(num_processes, prog, addr, env_extra, timeout)
        # rc 130 = KeyboardInterrupt: the user asked to stop, don't relaunch
        if rc in (0, 130) or attempt == max_restarts:
            return rc
        sys.stderr.write(
            f"[launch] group failed (rc {rc}); restart "
            f"{attempt + 1}/{max_restarts}\n"
        )
    return rc


def _run_group(num_processes: int, prog: List[str], coordinator_address: str,
               env_extra: Optional[dict], timeout: Optional[float]) -> int:
    """One process-group attempt."""
    cmds = build_commands(num_processes, prog)
    procs: List[subprocess.Popen] = []
    threads: List[threading.Thread] = []
    for rank, cmd in enumerate(cmds):
        env = dict(os.environ)
        env.update(env_extra or {})
        env.update({
            "PVA_COORDINATOR_ADDRESS": coordinator_address,
            "PVA_NUM_PROCESSES": str(num_processes),
            "PVA_PROCESS_ID": str(rank),
        })
        if rank == 0:
            p = subprocess.Popen(cmd, env=env)
        else:
            p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
            t = make_thread(target=_forward, args=(p.stdout, rank),
                            daemon=True)
            t.start()
            threads.append(t)
        procs.append(p)

    rc = 0
    deadline = (time.monotonic() + timeout) if timeout else None
    try:
        # any-child semantics: tear the group down as soon as ANY rank fails
        # (a dead peer leaves the others blocked in a collective forever),
        # with ONE group-level deadline rather than a per-process clock
        while True:
            codes = [p.poll() for p in procs]
            bad = next((c for c in codes if c), None)
            if bad is not None:
                rc = bad
                break
            if all(c == 0 for c in codes):
                break
            if deadline is not None and time.monotonic() > deadline:
                rc = 124
                break
            time.sleep(0.2)
    except KeyboardInterrupt:
        rc = rc or 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for t in threads:
            t.join(timeout=5)
    return rc


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pytorchvideo_accelerate_tpu.launch", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--num_processes", type=int, default=1,
                    help="local processes to spawn (accelerate --num_processes)")
    ap.add_argument("--coordinator_address", default="",
                    help="host:port of the jax.distributed coordinator "
                         "(default: 127.0.0.1 with a free port)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="kill the group after this many seconds (per attempt)")
    ap.add_argument("--max_restarts", type=int, default=0,
                    help="relaunch the whole group on failure up to N times "
                         "(pair with --resume_from_checkpoint auto)")
    ap.add_argument("prog", nargs=argparse.REMAINDER,
                    help="script.py + args, or args for the default "
                         "training module")
    args = ap.parse_args(argv)
    prog = args.prog
    if prog and prog[0] == "--":
        prog = prog[1:]
    return launch(args.num_processes, prog,
                  coordinator_address=args.coordinator_address,
                  timeout=args.timeout, max_restarts=args.max_restarts)


if __name__ == "__main__":
    sys.exit(main())
