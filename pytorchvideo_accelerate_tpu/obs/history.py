"""Bounded time-series history over `Registry.scrape()` ticks.

`/metrics` and `Registry.scrape()` are instants: the moment the scrape
returns, the number is gone. Every consumer that needed *time* rebuilt it
privately — the autoscaler's `SignalReader` ran its own EWMAs, the canary
kept its own windows, and an SLO question like "has p99 been burning for
five minutes?" had no substrate at all. `MetricsHistory` is that
substrate: a bounded ring of (timestamp, flat scrape dict) ticks, with
window reads (`series` / `window_mean` / `rate` / `ewma`) over the SAME
keys the scrape emits (``name{label="v"}`` flat-key format, histogram
``_sum``/``_count`` pairs).

Consumers (docs/OBSERVABILITY.md § metrics history):

- `obs/alerts.py` evaluates multi-window burn-rate rules over it;
- the serving server exposes it as `GET /history`;
- `fleet.control.signals.SignalReader` reads its EWMAs from the shared
  history instead of recomputing per-reader state.

Ticks are pulled, not pushed: whoever owns a control cadence (the alert
engine's tick, the server's scrape, a test) calls `tick()`. The ring is a
plain list under one factory lock — capacity is small (hundreds of
ticks), and eviction is O(1) amortized via an index, not a rebuild.

Arming discipline (`utils/sync.py`): module-level `get_history()` is one
global read; nothing ticks until something is armed via `configure()`.
Stdlib-only: importable without jax from the serving process.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

_DEFAULT: Optional["MetricsHistory"] = None


@shared_state("_ticks", "_head", "_total_ticks")
class MetricsHistory:
    """Ring of (ts, scrape) ticks; reads race the ticker thread."""

    def __init__(self, registry=None, capacity: int = 512,
                 prefix: str = "pva_"):
        from pytorchvideo_accelerate_tpu.obs.registry import get_registry

        if capacity < 2:
            raise ValueError("history needs >= 2 ticks to hold a window")
        self._lock = make_lock("obs.MetricsHistory._lock")
        self.capacity = int(capacity)
        self.prefix = prefix
        self.registry = registry if registry is not None else get_registry()
        self._ticks: List[Tuple[float, Dict[str, float]]] = []
        self._head = 0  # ring start index once the list is full
        self._total_ticks = 0

    # --- writing ------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Dict[str, float]:
        """Scrape the registry and append one tick (evicting the oldest
        past capacity). Returns the scrape so a caller can piggyback."""
        snap = self.registry.scrape(self.prefix)
        ts = time.time() if now is None else float(now)
        with self._lock:
            if len(self._ticks) < self.capacity:
                self._ticks.append((ts, snap))
            else:
                self._ticks[self._head] = (ts, snap)
                self._head = (self._head + 1) % self.capacity
            self._total_ticks += 1
        return snap

    # --- reading ------------------------------------------------------------

    def _ordered(self) -> List[Tuple[float, Dict[str, float]]]:
        with self._lock:
            if len(self._ticks) < self.capacity:
                return list(self._ticks)
            return self._ticks[self._head:] + self._ticks[:self._head]

    def series(self, key: str, window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """(ts, value) points for one flat scrape key, oldest first,
        optionally restricted to the trailing `window_s` seconds.

        A bare metric name that only exists labeled (``key{...}``) reads
        as the SUM across its label values per tick — so a rule over
        ``pva_serving_shed_total`` sees all shed causes without having to
        enumerate ``{state=...}`` variants."""
        ticks = self._ordered()
        if window_s is not None:
            cutoff = (time.time() if now is None else now) - window_s
            ticks = [t for t in ticks if t[0] >= cutoff]
        out: List[Tuple[float, float]] = []
        probe = key + "{"
        for ts, snap in ticks:
            if key in snap:
                out.append((ts, snap[key]))
                continue
            vals = [v for k, v in snap.items() if k.startswith(probe)]
            if vals:
                out.append((ts, sum(vals)))
        return out

    def latest(self, key: str) -> Optional[float]:
        for ts, snap in reversed(self._ordered()):
            if key in snap:
                return snap[key]
        return None

    def window_mean(self, key: str, window_s: float,
                    now: Optional[float] = None) -> Optional[float]:
        pts = self.series(key, window_s=window_s, now=now)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def rate(self, key: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second increase of a (monotonic counter) key over the
        window; None without >= 2 points or with zero elapsed time.
        Clamped at 0 so a counter reset never reads as a negative rate."""
        pts = self.series(key, window_s=window_s, now=now)
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return None
        return max(0.0, (v1 - v0) / (t1 - t0))

    def ratio(self, num_key: str, den_key: str, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
        """delta(num)/delta(den) over the window — the error-fraction /
        shed-fraction read for counter pairs (and histogram _sum/_count
        pairs, which gives a true windowed mean latency). None when the
        denominator did not move."""
        npts = self.series(num_key, window_s=window_s, now=now)
        dpts = self.series(den_key, window_s=window_s, now=now)
        if len(npts) < 2 or len(dpts) < 2:
            return None
        dden = dpts[-1][1] - dpts[0][1]
        if dden <= 0:
            return None
        return max(0.0, (npts[-1][1] - npts[0][1])) / dden

    def ewma(self, key: str, halflife_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Irregular-interval EWMA over the whole retained series (the
        SignalReader read): alpha per step from the actual tick gap."""
        pts = self.series(key)
        if not pts:
            return None
        acc = pts[0][1]
        prev_ts = pts[0][0]
        for ts, v in pts[1:]:
            dt = max(0.0, ts - prev_ts)
            alpha = 1.0 - 0.5 ** (dt / halflife_s) if halflife_s > 0 else 1.0
            acc += alpha * (v - acc)
            prev_ts = ts
        return acc

    # --- export -------------------------------------------------------------

    def occupancy(self) -> int:
        with self._lock:
            return len(self._ticks)

    def total_ticks(self) -> int:
        with self._lock:
            return self._total_ticks

    def snapshot(self) -> Dict:
        ticks = self._ordered()
        return {
            "capacity": self.capacity,
            "occupancy": len(ticks),
            "total_ticks": self.total_ticks(),
            "span_s": (ticks[-1][0] - ticks[0][0]) if len(ticks) > 1 else 0.0,
        }

    def to_json(self, keys: Optional[List[str]] = None,
                window_s: Optional[float] = None) -> Dict:
        """The `GET /history` payload: ring metadata + per-key series
        (every retained key when `keys` is None — bounded by capacity, so
        the response is bounded too)."""
        ticks = self._ordered()
        if window_s is not None:
            cutoff = time.time() - window_s
            ticks = [t for t in ticks if t[0] >= cutoff]
        if keys is None:
            seen = {}
            for _, snap in ticks:
                seen.update(dict.fromkeys(snap))
            keys = sorted(seen)
        out = self.snapshot()
        out["series"] = {
            k: [[round(ts, 3), v] for ts, snap in ticks
                if (v := snap.get(k)) is not None]
            for k in keys}
        return out


def get_history() -> Optional[MetricsHistory]:
    return _DEFAULT


def configure(enabled: bool = True, **kwargs) -> Optional[MetricsHistory]:
    """Arm (or disarm) the process-default history ring."""
    global _DEFAULT
    _DEFAULT = MetricsHistory(**kwargs) if enabled else None
    return _DEFAULT
