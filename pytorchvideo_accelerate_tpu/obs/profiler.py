"""On-demand device profiler capture (pva-tpu-hbm layer c).

`TrainConfig.profile` captures a fixed early-step window and nothing
else: a live incident — serving p99 burning NOW, a step-time regression
appearing mid-run — had no way to get a device profile out of the
process. This module adds exactly that, two triggers over one capture
primitive:

- ``POST /profile?seconds=N`` on the serving server: a background
  capture window on a live process (409 while one is running — the
  profiler is a singleton resource);
- ``--obs.profile_steps A..B`` in the trainer: a run-relative step
  window (same origin as the early-step `profile` flag: step 0 is this
  run's first step, so a resumed run profiles its warm steps, not a
  global step count it never sees).

Captures are written ATOMICALLY under `output_dir`: the trace streams
into a dot-prefixed temp dir and is `os.replace`d to its final name
(`profile_<tag>/`) only after `stop_trace()` returns — a crashed or
half-done capture can never be mistaken for a complete one, and
`pva-tpu-trace` can merge complete captures with the trace rings by
timestamp. The flight ring gets start/stop events, so profile windows
line up against the incident timeline.

Arming discipline: the module-level hooks are one global read while
disarmed. jax is imported lazily inside the capture calls only — the
module stays stdlib-importable (serving worker threads, tests without a
device) and a backend without a profiler degrades to a recorded refusal,
never a crash.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Dict, Optional

from pytorchvideo_accelerate_tpu.utils.sync import (
    make_lock,
    make_thread,
    shared_state,
)

_DEFAULT: Optional["ProfilerCapture"] = None


def parse_steps(spec: str) -> Optional[tuple]:
    """"A..B" -> (A, B) run-relative step window; None for the empty
    spec. Raises ValueError on malformed/inverted windows (config-time
    validation, not a mid-run surprise)."""
    if not spec:
        return None
    parts = spec.split("..")
    if len(parts) != 2:
        raise ValueError(
            f"profile_steps must look like 'A..B', got {spec!r}")
    a, b = int(parts[0]), int(parts[1])
    if a < 0 or b <= a:
        raise ValueError(
            f"profile_steps window must satisfy 0 <= A < B, got {spec!r}")
    return a, b


@shared_state("_active_tag", "_tmp_dir", "_captures")
class ProfilerCapture:
    """One jax.profiler trace window at a time, atomically published."""

    def __init__(self, output_dir: str, recorder=None):
        self._lock = make_lock("obs.ProfilerCapture._lock")
        self.output_dir = output_dir
        self.recorder = recorder
        self._active_tag: Optional[str] = None
        self._tmp_dir: Optional[str] = None
        self._captures: int = 0
        self._thread = None

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._active_tag is not None

    def start(self, tag: Optional[str] = None) -> bool:
        """Open a trace window; False (not an exception) when one is
        already open or the backend has no profiler."""
        tag = tag or time.strftime("%Y%m%d-%H%M%S")
        with self._lock:
            if self._active_tag is not None:
                return False
            tmp = os.path.join(self.output_dir, f".profile_tmp_{tag}")
            self._active_tag, self._tmp_dir = tag, tmp
        try:
            import jax

            os.makedirs(tmp, exist_ok=True)
            jax.profiler.start_trace(tmp)
        except Exception as e:
            with self._lock:
                self._active_tag = self._tmp_dir = None
            shutil.rmtree(tmp, ignore_errors=True)
            if self.recorder is not None:
                self.recorder.warn("profiler capture refused",
                                   error=f"{type(e).__name__}: {e}")
            return False
        if self.recorder is not None:
            self.recorder.record("profile", "start", tag=tag)
        return True

    def stop(self) -> Optional[str]:
        """Close the window and publish it atomically; returns the final
        directory, or None when nothing was open / publishing failed."""
        with self._lock:
            tag, tmp = self._active_tag, self._tmp_dir
            self._active_tag = self._tmp_dir = None
        if tag is None:
            return None
        final = os.path.join(self.output_dir, f"profile_{tag}")
        try:
            import jax

            jax.profiler.stop_trace()
            # the capture only exists once this rename lands — readers
            # never see a partial trace directory
            if os.path.isdir(final):
                shutil.rmtree(final, ignore_errors=True)
            os.replace(tmp, final)
        except Exception as e:
            shutil.rmtree(tmp, ignore_errors=True)
            if self.recorder is not None:
                self.recorder.warn("profiler capture lost",
                                   tag=tag, error=f"{type(e).__name__}: {e}")
            return None
        with self._lock:
            self._captures += 1
        if self.recorder is not None:
            self.recorder.record("profile", "stop", tag=tag, dir=final)
        return final

    def capture_for(self, seconds: float,
                    tag: Optional[str] = None) -> Optional[str]:
        """The POST /profile shape: start now, stop after `seconds` on a
        background daemon thread. Returns the pending capture's tag, or
        None when a window is already open / the backend refused."""
        tag = tag or time.strftime("%Y%m%d-%H%M%S")
        if not self.start(tag=tag):
            return None

        def _worker():
            time.sleep(max(0.0, float(seconds)))
            self.stop()

        self._thread = make_thread(target=_worker, daemon=True,
                                   name="pva-profile-capture")
        self._thread.start()
        return tag

    def join(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    def snapshot(self) -> Dict:
        with self._lock:
            return {"busy": self._active_tag is not None,
                    "active_tag": self._active_tag,
                    "captures": self._captures,
                    "output_dir": self.output_dir}


def get_profiler() -> Optional[ProfilerCapture]:
    return _DEFAULT


def configure(enabled: bool = True,
              output_dir: Optional[str] = None,
              **kwargs) -> Optional[ProfilerCapture]:
    """Arm (or disarm) the process-default capture singleton."""
    global _DEFAULT
    if not enabled or output_dir is None:
        _DEFAULT = None
        return None
    _DEFAULT = ProfilerCapture(output_dir, **kwargs)
    return _DEFAULT
