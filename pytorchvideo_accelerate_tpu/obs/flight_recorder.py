"""Flight recorder: a bounded in-memory ring of recent telemetry events,
dumped to `<output_dir>/flight_record.json` when the process dies.

The black box for the four asynchronous layers (decode pool, device
prefetcher, train loop, serving batcher): spans, sampled metrics, warnings,
watchdog stalls and exceptions all append here cheaply (one deque append
under a lock; the deque's maxlen bounds memory forever). Three dump paths:

- **exception**: `Trainer.fit()` dumps explicitly on any raising epoch loop
  (complementing the partial-profile flush in trainer/loop.py), and
  `install()` chains `sys.excepthook` for crashes outside fit;
- **SIGTERM**: `install()` chains a handler so an external kill (the tier-1
  870s timeout's `timeout -k`) leaves evidence behind instead of dying
  blind;
- **watchdog**: `obs/watchdog.py` dumps when progress stalls, BEFORE any
  external timeout fires.

The dumped file is what `pva-tpu-doctor`'s obs snapshot reads from a second
shell (utils/device_doctor.obs_snapshot) — the wedge's evidence file.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import List, Optional

from pytorchvideo_accelerate_tpu.utils.sync import make_rlock, shared_state

_MIN_CAPACITY = 16


@shared_state("_events", "_output_dir", "_installed")
class FlightRecorder:
    """Thread-safe bounded event ring + crash-dump plumbing."""

    def __init__(self, capacity: int = 512):
        # RLock, not Lock: the SIGTERM handler runs ON the main thread and
        # calls record()/dump() — if the signal interrupted that same
        # thread inside record(), a plain lock would deadlock and the
        # process would die to SIGKILL with no flight record (the exact
        # failure this file exists to prevent)
        self._lock = make_rlock("FlightRecorder._lock")
        self._events: deque = deque(maxlen=max(capacity, _MIN_CAPACITY))
        self._output_dir = ""
        self._installed = False

    # --- recording --------------------------------------------------------

    def record(self, kind: str, name: str, **fields) -> None:
        evt = {"ts": round(time.time(), 6),
               "thread": threading.current_thread().name,
               "kind": kind, "name": str(name)}
        if fields:
            evt.update(fields)
        with self._lock:
            self._events.append(evt)

    def warn(self, message: str, **fields) -> None:
        self.record("warning", message, **fields)

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._events = deque(self._events,
                                 maxlen=max(capacity, _MIN_CAPACITY))

    def snapshot(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            events = list(self._events)
        return events[-last:] if last else events

    # --- dumping ----------------------------------------------------------

    def default_path(self) -> Optional[str]:
        # dump() runs on watchdog/excepthook/handler threads while install()
        # (re)points the output dir from the main thread — same lock as the
        # ring (pva-tpu-tsan: bare read of `_output_dir` raced `install`)
        with self._lock:
            out_dir = self._output_dir
        if not out_dir:
            return None
        return os.path.join(out_dir, "flight_record.json")

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write the ring to `path` (default: the installed output dir).
        Returns the written path, or None when there is nowhere to write or
        the write failed — a dying process must not die twice over its own
        black box."""
        path = path or self.default_path()
        if not path:
            return None
        payload = {"dumped_at": round(time.time(), 6), "pid": os.getpid(),
                   "events": self.snapshot()}
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f, indent=1, default=str)
        except OSError:
            return None
        return path

    # --- crash hooks ------------------------------------------------------

    def install(self, output_dir: str) -> None:
        """Point dumps at `output_dir` and (once per process) chain
        sys.excepthook + SIGTERM so an uncaught crash or an external kill
        flushes the ring. Re-installs just update the output dir."""
        with self._lock:
            if output_dir:
                self._output_dir = output_dir
            if self._installed:
                return
            self._installed = True

        prev_hook = sys.excepthook

        def hook(exc_type, exc, tb):
            self.record("exception", exc_type.__name__,
                        message=str(exc)[:500])
            self.dump()
            prev_hook(exc_type, exc, tb)

        sys.excepthook = hook

        try:
            prev = signal.getsignal(signal.SIGTERM)

            def on_term(signum, frame):
                self.record("signal", "SIGTERM")
                self.dump()
                if callable(prev):
                    prev(signum, frame)
                elif prev is signal.SIG_IGN:
                    return  # preserve an ignore disposition: dump, survive
                else:  # default disposition: re-raise the default death
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, on_term)
        except (ValueError, OSError):  # not the main thread: hooks only
            pass


_DEFAULT = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _DEFAULT
