"""`pva-tpu-trace`: merge trace rings + flight records into one timeline.

Each process of a run (the trainer, N serving replicas, the bench fleet
child) keeps its own bounded trace ring (obs/trace.py, dumped as
`trace_ring.json`) and its own flight-recorder ring (`flight_record.json`).
Diagnosing a cross-process request — a p99 sample that crossed the router,
an HTTP hop, and a replica's scheduler — needs all of them on ONE
wall-clock axis. This tool does exactly that:

    pva-tpu-trace --out merged.json run_a/trace_ring.json \\
        run_b/trace_ring.json run_a/flight_record.json

- trace rings (`{"traceEvents": [...]}`) merge verbatim: their events
  already carry wall-clock microsecond timestamps and the recording pid;
- flight records (`{"events": [...]}`) convert to Perfetto INSTANT events
  (`ph: "i"`), so watchdog stalls, warnings, and membership flaps line up
  against the request spans that surrounded them;
- output is Chrome trace-event JSON, sorted by timestamp — load it in
  Perfetto / chrome://tracing, or grep it for a `trace_id` surfaced by a
  latency-histogram exemplar or `/stats` `slowest_traces`.

The summary line (stdout) reports event/trace/process counts and the
slowest root spans, so scripts can sanity-check a merge without opening
the UI. Stdlib-only; never imports jax.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence

_FLIGHT_TID = 0  # flight-record events carry thread NAMES, not idents


def flight_to_events(record: dict) -> List[dict]:
    """Convert one flight-record dump into Perfetto instant events."""
    pid = record.get("pid", 0)
    out = []
    for evt in record.get("events", ()):
        args = {k: v for k, v in evt.items()
                if k not in ("ts", "kind", "name")}
        out.append({
            "name": f"{evt.get('kind', 'event')}:{evt.get('name', '?')}",
            "ph": "i",
            "s": "p",  # process-scoped instant
            "ts": round(float(evt.get("ts", 0.0)) * 1e6, 1),
            "pid": pid,
            "tid": _FLIGHT_TID,
            "args": args,
        })
    return out


def events_of(payload: dict) -> List[dict]:
    """Events from one parsed input, whichever shape it is."""
    if "traceEvents" in payload:
        return list(payload["traceEvents"])
    if "events" in payload:
        return flight_to_events(payload)
    raise ValueError(
        "input is neither a trace ring ('traceEvents') nor a flight "
        "record ('events')")


def merge_exports(payloads: Sequence[dict]) -> dict:
    """Merge already-parsed payloads into one timestamp-sorted timeline."""
    events: List[dict] = []
    for payload in payloads:
        events.extend(events_of(payload))
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_paths(paths: Sequence[str]) -> dict:
    """Merge the readable inputs; unreadable/torn ones (a crash dump cut
    off mid-write is exactly the situation this tool serves) are skipped
    with a stderr warning. Raises only when NOTHING could be loaded."""
    payloads = []
    skipped = []
    for path in paths:
        try:
            with open(path) as f:
                payloads.append(json.load(f))
        except (OSError, ValueError) as e:
            skipped.append(path)
            print(f"pva-tpu-trace: skipping {path}: {e}", file=sys.stderr)
    if not payloads:
        raise ValueError(
            f"no readable inputs among {list(paths)} "
            f"({len(skipped)} skipped)")
    return merge_exports(payloads)


def summarize(merged: dict, slowest: int = 5) -> dict:
    """Counts + slowest roots: the scriptable sanity check of a merge."""
    events = merged.get("traceEvents", [])
    traces: Dict[str, set] = {}
    roots: List[dict] = []
    for e in events:
        args = e.get("args", {})
        tid = args.get("trace_id")
        if tid:
            traces.setdefault(tid, set()).add(e.get("pid"))
            if "parent_id" not in args and e.get("ph") == "X":
                roots.append(e)
    roots.sort(key=lambda e: -float(e.get("dur", 0.0)))
    return {
        "events": len(events),
        "traces": len(traces),
        "pids": sorted({e.get("pid") for e in events}),
        # traces whose events span >1 process: the cross-process proof
        "traces_multiprocess": sum(
            1 for pids in traces.values() if len(pids) > 1),
        "slowest": [{"trace_id": e["args"]["trace_id"], "name": e["name"],
                     "dur_ms": round(float(e.get("dur", 0.0)) / 1e3, 3)}
                    for e in roots[:slowest]],
    }


def linked_traces(merged: dict, require_names: Sequence[str] = (),
                  min_pids: int = 1) -> List[str]:
    """Trace ids whose events span >= `min_pids` processes AND include
    every name in `require_names` — how the bench asserts "≥1 sampled
    request spanning router→replica→engine"."""
    by_trace: Dict[str, dict] = {}
    for e in merged.get("traceEvents", []):
        tid = e.get("args", {}).get("trace_id")
        if not tid:
            continue
        rec = by_trace.setdefault(tid, {"pids": set(), "names": set()})
        rec["pids"].add(e.get("pid"))
        rec["names"].add(e.get("name"))
    return sorted(
        tid for tid, rec in by_trace.items()
        if len(rec["pids"]) >= min_pids
        and all(n in rec["names"] for n in require_names))


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="pva-tpu-trace",
        description="merge trace rings + flight records from N processes "
                    "into one Chrome/Perfetto timeline "
                    "(docs/OBSERVABILITY.md § distributed tracing)")
    ap.add_argument("inputs", nargs="+",
                    help="trace_ring.json / flight_record.json files")
    ap.add_argument("--out", default="",
                    help="write the merged timeline here (omit to only "
                         "print the summary)")
    ap.add_argument("--slowest", type=int, default=5,
                    help="how many slowest root spans to summarize")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    try:
        merged = merge_paths(args.inputs)
    except (OSError, ValueError) as e:
        print(f"pva-tpu-trace: {e}", file=sys.stderr)
        return 2
    if args.out:
        with open(args.out, "w") as f:
            json.dump(merged, f)
    summary = summarize(merged, slowest=args.slowest)
    if args.out:
        summary["out"] = args.out
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
