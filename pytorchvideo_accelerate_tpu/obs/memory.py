"""Device-memory ledger: per-component HBM byte accounting (pva-tpu-hbm).

Every byte figure the control plane used to act on was a *declared
estimate* (`ring_bytes(geom)`, `footprint_mb` at model registration).
The ledger makes device memory an observed truth: the real allocation
sites — trainer state (params/opt/EMA), the guard LKG ring, the device
prefetch ring, serving weight pins + compiled-bucket caches, streaming
ring pools — register their actual array bytes here, and the ledger
cross-checks the attributed sum live against the backend's
`device.memory_stats()` (`bytes_in_use` / `peak_bytes_in_use`) where the
platform exposes it (TPU/GPU; the CPU backend does not, and the ledger
NEVER fakes device bytes — `source` stays "estimate").

The residual discipline is PR-3's `obs/unattributed_s` applied to bytes:
`unattributed_bytes = bytes_in_use - sum(components)` is published
explicitly instead of silently absorbed, so a growing residual is a
visible accounting bug, not a hidden leak. Declared-vs-measured drift
past `drift_tol` is itself a gauge (`pva_hbm_drift_frac{component=}`):
when an estimate lies, the lie is a metric.

Exported surface (docs/OBSERVABILITY.md § memory ledger):

- gauges `pva_hbm_bytes{component=}` (+ the explicit ``unattributed``
  component), `pva_hbm_bytes_in_use`, `pva_hbm_peak_bytes`,
  `pva_hbm_attributed_frac`, `pva_hbm_drift_frac{component=}`;
- watermark warnings into the flight ring when `bytes_in_use` crosses
  `watermark_frac` of the backend's `bytes_limit` (edge-triggered — one
  warning per excursion, re-armed on recovery);
- `measured_bytes(component)` / `source()` for admission paths
  (`SessionTable`, `ModelBudget`): *measured* ledger bytes on device,
  declared estimates as the documented CPU/test fallback.

Arming discipline (`utils/sync.py`): the module-level `register()` /
`release()` hooks at the allocation sites are ONE module-global read +
`None` check while disarmed — no dict, no lock, no jax. `configure()`
arms the process-default ledger; tests construct private instances.

Stdlib-only at import time: jax is imported lazily inside
`default_device_stats()` and only when a caller actually asks the
backend (obs/ must stay importable from worker threads without jax).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

# The armed process-default ledger or None. Module-global by design (the
# utils/sync.py `_runtime` pattern): the disarmed hot path at every
# allocation site is one load + a None check.
_DEFAULT: Optional["MemoryLedger"] = None


def default_device_stats() -> Optional[Dict[str, int]]:
    """`memory_stats()` of device 0, or None when the backend does not
    expose it (CPU) or jax is absent entirely. Never raises: a dying
    probe must not take an allocation site down with it."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats()
    except Exception:
        return None
    if not stats or "bytes_in_use" not in stats:
        return None
    return {k: int(v) for k, v in stats.items()
            if isinstance(v, (int, float))}


def tree_nbytes(tree) -> int:
    """Total `.nbytes` over a pytree of arrays — jax.tree_util when
    available, a stdlib container walk otherwise (tests without jax)."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        leaves = _walk_leaves(tree)
    return sum(int(getattr(leaf, "nbytes", 0)) for leaf in leaves)


def _walk_leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _walk_leaves(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _walk_leaves(v)
    else:
        yield tree


@shared_state("_bytes", "_declared", "_peak_attributed", "_over_watermark")
class MemoryLedger:
    """Per-component device-byte accounting with a live backend
    cross-check. Thread-safe: allocation sites (streaming pool builds,
    serving weight pins) race scrape ticks and the doctor's snapshot."""

    def __init__(self, registry=None, recorder=None, *,
                 watermark_frac: float = 0.92,
                 drift_tol: float = 0.25,
                 stats_fn: Optional[Callable[[], Optional[Dict[str, int]]]]
                 = None):
        from pytorchvideo_accelerate_tpu.obs.registry import get_registry

        self._lock = make_lock("obs.MemoryLedger._lock")
        self._bytes: Dict[str, int] = {}
        self._declared: Dict[str, int] = {}
        self._peak_attributed = 0
        self._over_watermark = False  # edge trigger for the watermark warn
        self.watermark_frac = float(watermark_frac)
        self.drift_tol = float(drift_tol)
        self.registry = registry if registry is not None else get_registry()
        self.recorder = recorder
        self._stats_fn = stats_fn if stats_fn is not None \
            else default_device_stats
        self._g_bytes = self.registry.gauge(
            "pva_hbm_bytes", "attributed device bytes per component "
            "(component=unattributed is the residual vs bytes_in_use)",
            labelnames=("component",))
        self._g_drift = self.registry.gauge(
            "pva_hbm_drift_frac", "relative declared-vs-measured drift "
            "per component (0 when the estimate is honest)",
            labelnames=("component",))
        self._g_in_use = self.registry.gauge(
            "pva_hbm_bytes_in_use", "backend bytes_in_use (0 = backend "
            "exposes no memory_stats; see pva_hbm_attributed_frac)")
        self._g_peak = self.registry.gauge(
            "pva_hbm_peak_bytes", "backend peak_bytes_in_use, or the peak "
            "attributed sum when the backend exposes no memory_stats")
        self._g_frac = self.registry.gauge(
            "pva_hbm_attributed_frac",
            "attributed / bytes_in_use (1.0 when no backend stats: the "
            "ledger is then the only accounting there is)")
        # live reads: the scrape sees current stats without a tick cycle
        self._g_in_use.set_function(lambda: (self.device_stats() or {})
                                    .get("bytes_in_use", 0))
        self._g_peak.set_function(lambda: self.peak_bytes())
        self._g_frac.set_function(lambda: self.attributed_frac())
        self._g_bytes.set_function(lambda: self.unattributed_bytes(),
                                   component="unattributed")

    # --- accounting ---------------------------------------------------------

    def register(self, component: str, nbytes: int,
                 declared: Optional[int] = None) -> None:
        """Add `nbytes` of live device allocation to `component`;
        `declared` is the estimate the caller would have used before this
        ledger existed (drives the drift gauge)."""
        n = int(nbytes)
        with self._lock:
            self._bytes[component] = self._bytes.get(component, 0) + n
            if declared is not None:
                self._declared[component] = (
                    self._declared.get(component, 0) + int(declared))
            total = sum(self._bytes.values())
            if total > self._peak_attributed:
                self._peak_attributed = total
            cur = self._bytes[component]
            dec = self._declared.get(component)
        self._g_bytes.set(cur, component=component)
        if dec:
            self._g_drift.set(abs(cur - dec) / dec, component=component)
        self._check_watermark()

    def release(self, component: str, nbytes: Optional[int] = None,
                declared: Optional[int] = None) -> None:
        """Return bytes to the pool; `nbytes=None` clears the component.
        Clamped at zero — a double release is an accounting bug, not a
        negative gauge."""
        with self._lock:
            if nbytes is None:
                self._bytes.pop(component, None)
                self._declared.pop(component, None)
                cur, dec = 0, None
            else:
                cur = max(0, self._bytes.get(component, 0) - int(nbytes))
                self._bytes[component] = cur
                if declared is not None:
                    dec = max(0,
                              self._declared.get(component, 0)
                              - int(declared))
                    self._declared[component] = dec
                else:
                    dec = self._declared.get(component)
        self._g_bytes.set(cur, component=component)
        if dec:
            self._g_drift.set(abs(cur - dec) / dec, component=component)

    def component_bytes(self, component: str) -> int:
        with self._lock:
            return self._bytes.get(component, 0)

    def attributed_bytes(self) -> int:
        with self._lock:
            return sum(self._bytes.values())

    # --- backend cross-check ------------------------------------------------

    def device_stats(self) -> Optional[Dict[str, int]]:
        try:
            return self._stats_fn()
        except Exception:
            return None

    def source(self) -> str:
        """"measured" when the backend exposes memory_stats, else
        "estimate" — the label every headline that carries ledger bytes
        must carry too (never fake device bytes on a CPU host)."""
        return "measured" if self.device_stats() is not None else "estimate"

    def measured_bytes(self, component: str) -> Optional[int]:
        """Ledger bytes for `component` IF this host measures device
        memory; None on estimate-only hosts (admission falls back to the
        caller's declared figure — the documented CPU/test path)."""
        if self.device_stats() is None:
            return None
        return self.component_bytes(component)

    def peak_bytes(self) -> int:
        stats = self.device_stats()
        if stats is not None and "peak_bytes_in_use" in stats:
            return stats["peak_bytes_in_use"]
        with self._lock:
            return self._peak_attributed

    def unattributed_bytes(self) -> int:
        stats = self.device_stats()
        if stats is None:
            return 0
        return max(0, stats.get("bytes_in_use", 0) - self.attributed_bytes())

    def attributed_frac(self) -> float:
        stats = self.device_stats()
        if stats is None or not stats.get("bytes_in_use"):
            return 1.0
        return min(1.0, self.attributed_bytes() / stats["bytes_in_use"])

    def _check_watermark(self) -> None:
        stats = self.device_stats()
        limit = (stats or {}).get("bytes_limit")
        if not limit:
            return
        over = stats.get("bytes_in_use", 0) >= self.watermark_frac * limit
        with self._lock:
            fire = over and not self._over_watermark
            self._over_watermark = over
        if fire and self.recorder is not None:
            self.recorder.warn(
                "hbm watermark crossed",
                bytes_in_use=stats.get("bytes_in_use", 0),
                bytes_limit=limit, watermark_frac=self.watermark_frac)

    # --- snapshots ----------------------------------------------------------

    def drift(self) -> Dict[str, float]:
        """component -> |measured - declared| / declared, for components
        that declared an estimate."""
        with self._lock:
            return {c: abs(self._bytes.get(c, 0) - d) / d
                    for c, d in self._declared.items() if d}

    def snapshot(self) -> Dict:
        """The doctor-facing view: per-component bytes, the residual,
        drift offenders, and the provenance label."""
        stats = self.device_stats()
        with self._lock:
            components = dict(self._bytes)
            peak_att = self._peak_attributed
        drift = self.drift()
        out = {
            "source": "measured" if stats is not None else "estimate",
            "components": components,
            "attributed_bytes": sum(components.values()),
            "unattributed_bytes": self.unattributed_bytes(),
            "attributed_frac": self.attributed_frac(),
            "peak_bytes": (stats or {}).get("peak_bytes_in_use", peak_att),
            "drift": drift,
            "drift_over_tol": sorted(c for c, d in drift.items()
                                     if d > self.drift_tol),
        }
        if stats is not None:
            out["bytes_in_use"] = stats.get("bytes_in_use", 0)
            if "bytes_limit" in stats:
                out["bytes_limit"] = stats["bytes_limit"]
        return out


# --- module-level arming ----------------------------------------------------

def get_ledger() -> Optional[MemoryLedger]:
    return _DEFAULT


def configure(enabled: bool = True, **kwargs) -> Optional[MemoryLedger]:
    """Arm (or disarm, with enabled=False) the process-default ledger.
    kwargs pass through to `MemoryLedger` (tests inject `stats_fn`)."""
    global _DEFAULT
    _DEFAULT = MemoryLedger(**kwargs) if enabled else None
    return _DEFAULT


def register(component: str, nbytes: int,
             declared: Optional[int] = None) -> None:
    """Allocation-site hook; disarmed this is one global read + return."""
    led = _DEFAULT
    if led is None:
        return
    led.register(component, nbytes, declared=declared)


def release(component: str, nbytes: Optional[int] = None,
            declared: Optional[int] = None) -> None:
    led = _DEFAULT
    if led is None:
        return
    led.release(component, nbytes, declared=declared)
