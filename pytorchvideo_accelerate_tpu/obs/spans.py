"""Near-zero-overhead wall-time spans: `with span("decode"): ...`.

The shared timing primitive of the telemetry spine. Every asynchronous
layer (decode pool, device prefetcher, train loop, serving flush thread)
wraps its blocking sections in named spans; the trainer drains the
aggregated window every `log_every` steps into a per-step wall-time
breakdown (`obs/input_wait_s`, `obs/h2d_s`, `obs/step_s`, ...) that flows
through the TrackerHub, and each completed span is also appended to the
flight recorder ring so a crash dump carries the recent timeline.

Design constraints, in order:

- **Overhead.** Disabled: `span()` returns a shared no-op context manager
  (two attribute loads, no allocation). Enabled: two `perf_counter` calls
  and one dict update under a lock — nanoseconds against a decode or a
  train step; the <1%-of-step-time budget holds either way.
- **Per-thread nesting.** Each thread keeps its own stack (threading.local)
  so concurrent producers/consumers never interleave; `current_stacks()`
  exposes every thread's open spans for the watchdog/doctor ("where is
  everyone stuck RIGHT NOW").
- **Consumer vs background attribution.** Spans recorded on worker threads
  (`h2d`, `decode`, ...) overlap the step loop's wall time; summing them
  with consumer-side spans would double-count. `BACKGROUND` names the
  worker-side set so the per-window sum check uses consumer spans only.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from pytorchvideo_accelerate_tpu.obs.trace import get_tracer as _get_tracer
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state


class _Noop:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class _Span:
    __slots__ = ("_c", "name", "_t0", "_trace")

    def __init__(self, collector: "SpanCollector", name: str):
        self._c = collector
        self.name = name
        self._t0 = 0.0
        self._trace = None

    def __enter__(self):
        self._c._push(self.name)
        # distributed-tracing hook (obs/trace.py): when the tracer is armed
        # AND this thread has an active trace context, the span doubles as
        # a trace event carrying trace/parent ids. Disarmed (or untraced):
        # one module-global read, no allocation.
        rt = _get_tracer()
        self._trace = rt.span_begin(self.name) if rt is not None else None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        tok = self._trace
        if tok is not None:
            tok.end(error=exc_type is not None)
            self._trace = None
        self._c._pop(self.name)
        self._c.observe(self.name, dt, error=exc_type is not None)
        return False


# span names recorded on worker threads: they run CONCURRENTLY with the
# step loop, so the per-window "components sum to wall time" check must
# exclude them (they are reported, just not summed)
BACKGROUND = frozenset({"h2d", "decode", "serve_flush",
                        "eval_input_wait", "eval_h2d"})

# per-SAMPLE spans are too chatty for the flight ring: one big batch would
# evict the step/warning/watchdog timeline a crash dump exists to preserve.
# They still aggregate into the window (and the per-window breakdown).
RECORDER_EXCLUDE = frozenset({"decode"})


@shared_state("_window", "recorder")
class SpanCollector:
    """Thread-safe span aggregator: per-name (total_s, count) windows plus
    per-thread open-span stacks."""

    def __init__(self, enabled: bool = True, recorder=None):
        self.enabled = enabled
        self.recorder = recorder  # FlightRecorder or None
        self._lock = make_lock("SpanCollector._lock")
        self._window: Dict[str, list] = {}
        self._tls = threading.local()
        # thread ident -> (thread name, live stack list); stacks are the
        # SAME list objects the threading.local holds, so reads see live
        # nesting without any per-span registration cost
        self._stacks: Dict[int, tuple] = {}

    # --- recording --------------------------------------------------------

    def span(self, name: str):
        """Context manager timing a named section (no-op when disabled)."""
        if not self.enabled:
            return _NOOP
        return _Span(self, name)

    def observe(self, name: str, dur_s: float, error: bool = False) -> None:
        """Record an externally-timed duration (the prefetcher measures its
        queue wait once and feeds both its own wait_s and this window)."""
        if not self.enabled:
            return
        with self._lock:
            entry = self._window.setdefault(name, [0.0, 0])
            entry[0] += dur_s
            entry[1] += 1
        rec = self.recorder
        if rec is not None and name not in RECORDER_EXCLUDE:
            if error:
                rec.record("span", name, dur_s=round(dur_s, 6), error=True)
            else:
                rec.record("span", name, dur_s=round(dur_s, 6))

    # --- nesting stacks ---------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
            t = threading.current_thread()
            with self._lock:
                if len(self._stacks) > 32:  # prune dead threads' leftovers
                    alive = {th.ident for th in threading.enumerate()}
                    for ident in [i for i, (_, s) in self._stacks.items()
                                  if not s and i not in alive]:
                        del self._stacks[ident]
                self._stacks[t.ident] = (t.name, st)
        return st

    def _push(self, name: str) -> None:
        self._stack().append(name)

    def _pop(self, name: str) -> None:
        st = self._stack()
        if st and st[-1] == name:
            st.pop()

    def current_stacks(self) -> Dict[str, list]:
        """{"thread_name-ident": [outer, ..., inner]} for every thread with
        an open span — the "where is everyone" view for watchdog/doctor
        dumps. Keys carry the ident because thread NAMES collide (both
        prefetchers run a "device-prefetch" worker), and a stall dump must
        never shadow the wedged thread's stack with a healthy namesake's."""
        with self._lock:
            return {f"{name}-{ident}": list(st)
                    for ident, (name, st) in self._stacks.items() if st}

    # --- draining ---------------------------------------------------------

    def pop_window(self) -> Dict[str, Tuple[float, int]]:
        """Drain and return {name: (total_s, count)} accumulated since the
        last drain (the per-`log_every` breakdown window)."""
        with self._lock:
            window, self._window = self._window, {}
        return {k: (v[0], v[1]) for k, v in window.items()}


_DEFAULT = SpanCollector()


def get_collector() -> SpanCollector:
    return _DEFAULT


def span(name: str):
    """`with span("decode"): ...` against the process-default collector."""
    return _DEFAULT.span(name)


def observe(name: str, dur_s: float) -> None:
    _DEFAULT.observe(name, dur_s)


def current_stacks() -> Dict[str, list]:
    return _DEFAULT.current_stacks()
