"""Process-wide metric registry with Prometheus text exposition.

The single source of numeric truth for counters/gauges/histograms across
data -> train -> serve: the serving server's `/metrics` endpoint renders a
`Registry` verbatim, `/stats` reads the SAME counter objects (so the two
surfaces cannot drift), and the trainer publishes its on-device health
gauges (grad/param norm, update ratio, non-finite-loss counter) here.

Deliberately tiny and stdlib-only — no prometheus_client dependency (the
container doesn't ship it), just the text exposition format v0.0.4 that
every scraper parses:

    # HELP name help text
    # TYPE name counter
    name{label="value"} 42
    hist_bucket{le="0.05"} 3 ... hist_sum 0.2 / hist_count 9

Thread-safety: one lock per metric; the registry itself locks only
creation/lookup. `inc`/`set`/`observe` on the hot path are a dict update
under a lock — nanoseconds against a network request or a train step.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from pytorchvideo_accelerate_tpu.utils.sync import make_lock


def _fmt(v: float) -> str:
    """Prometheus sample value: integers without a trailing .0, floats via
    repr (full precision), special-cased non-finites."""
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help or name
        self.labelnames = tuple(labelnames)
        # one lock CLASS for every metric instance (lockdep-style): the
        # sanitizer's order graph cares about metric-lock vs other-lock
        # ordering, not which of hundreds of counters was involved
        self._lock = make_lock("registry._Metric._lock")

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(labels)}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def header(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} {self.kind}\n")

    def render(self) -> str:  # pragma: no cover - interface
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic counter, optionally labeled (e.g. rejected{cause="503"}).

    Labels are the REQUIRED shape for families of related counts — the
    retry counters (`pva_retry_*{op=}`), fault fires
    (`pva_fault_injected_total{point=}`), guard ladder events
    (`pva_guard_events_total{action=}`), and quarantines
    (`pva_data_quarantined_total{site=}`) all label one family instead of
    minting name-mangled metric names per site; `total()` is the
    cross-label aggregate the `/stats`-style surfaces read. Same label
    surface as `Gauge` (tests/test_zguard.py locks it)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum over every label combination (the `/stats` aggregate view)."""
        with self._lock:
            return sum(self._values.values()) if self._values else 0.0

    def samples(self) -> Iterable[Tuple[Dict[str, str], float]]:
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            yield dict(zip(self.labelnames, key)), v

    def render(self) -> str:
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            if self.labelnames:  # no label combination seen yet: header only
                return self.header()
            items = [((), 0.0)]  # unlabeled counters render an explicit 0
        lines = [self.header()]
        for key, v in items:
            lines.append(
                f"{self.name}{_label_str(self.labelnames, key)} {_fmt(v)}\n")
        return "".join(lines)


class Gauge(_Metric):
    """Point-in-time value, optionally labeled (the fleet router publishes
    per-replica series: ``pva_fleet_outstanding{replica="r0"}``);
    `set_function` registers a live callback read at render/value time
    (queue depth, uptime). Unlabeled gauges keep the original one-sample
    surface — `set()`/`value()` with no labels."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._fns: Dict[Tuple[str, ...], Callable[[], float]] = {}

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def set_function(self, fn: Optional[Callable[[], float]],
                     **labels: str) -> None:
        """Register a live read callback; `None` deregisters it (owners of
        short-lived objects MUST clear their closure on close, or the
        registry pins them alive and scrapes stale values forever)."""
        key = self._key(labels)
        with self._lock:
            if fn is None:
                self._fns.pop(key, None)
            else:
                self._fns[key] = fn

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                return self._values.get(key, 0.0)
        try:  # callback runs OUTSIDE the lock: it may itself take locks
            return float(fn())
        except Exception:  # a dying callback must not break the scrape
            return float("nan")

    def samples(self) -> Iterable[Tuple[Dict[str, str], float]]:
        with self._lock:
            keys = sorted(set(self._values) | set(self._fns))
        for key in keys:
            labels = dict(zip(self.labelnames, key))
            yield labels, self.value(**labels)

    def render(self) -> str:
        with self._lock:
            keys = sorted(set(self._values) | set(self._fns))
        if not keys:
            if self.labelnames:  # no label combination seen yet
                return self.header()
            keys = [()]  # unlabeled gauges render an explicit 0
        lines = [self.header()]
        for key in keys:
            labels = dict(zip(self.labelnames, key))
            lines.append(f"{self.name}{_label_str(self.labelnames, key)} "
                         f"{_fmt(self.value(**labels))}\n")
        return "".join(lines)


DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)

# Per-FAMILY bucket boundaries, keyed by metric-name prefix (longest match
# wins). One serving tier wants sub-ms latency buckets, a batch tier wants
# multi-second ones — a single hardcoded ladder fits neither. Families are
# registered at configure time (`set_family_buckets`), consulted only when
# a histogram is created WITHOUT explicit buckets; an existing histogram
# never reshapes (cumulative counts cannot be re-binned).
_FAMILY_BUCKETS: Dict[str, Tuple[float, ...]] = {}


def set_family_buckets(prefix: str, buckets: Sequence[float]) -> None:
    """Declare default bucket boundaries for every histogram whose name
    starts with `prefix` (configure-time; see ServeConfig.latency_buckets_ms
    for the serving wiring)."""
    bs = tuple(sorted(float(b) for b in buckets))
    if not bs:
        raise ValueError("a bucket family needs at least one finite bound")
    _FAMILY_BUCKETS[prefix] = bs


def family_buckets(name: str,
                   default: Sequence[float] = DEFAULT_BUCKETS) -> Tuple[float, ...]:
    """Resolve the bucket ladder for `name`: longest registered family
    prefix, else `default`."""
    best = ""
    for prefix in _FAMILY_BUCKETS:
        if name.startswith(prefix) and len(prefix) > len(best):
            best = prefix
    return _FAMILY_BUCKETS[best] if best else tuple(default)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus convention: each `le` bucket
    counts every observation <= its bound; `+Inf` == `_count`).

    Buckets resolve per family when not given explicitly (`family_buckets`).
    `observe(value, trace_id=...)` additionally pins an OpenMetrics-style
    EXEMPLAR on the bucket the observation lands in — the last (trace_id,
    value, timestamp) per bucket — so the top latency bucket names the
    trace of a REAL slow request (the exemplar→trace workflow,
    docs/OBSERVABILITY.md). Exemplar rendering is behind a flag
    (`render(exemplars=True)`): the default text output stays plain
    Prometheus v0.0.4, parseable by every existing scraper and test."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help)
        if buckets is None:
            buckets = family_buckets(name)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one finite bucket")
        self._counts = [0] * (len(self.buckets) + 1)  # + the +Inf bucket
        self._sum = 0.0
        # bucket index -> (trace_id, value, unix_ts); last observation wins
        self._exemplars: List[Optional[Tuple[str, float, float]]] = (
            [None] * (len(self.buckets) + 1))

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        v = float(value)
        i = len(self.buckets)
        for j, b in enumerate(self.buckets):
            if v <= b:
                i = j
                break
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            if trace_id:
                self._exemplars[i] = (str(trace_id), v, time.time())

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def exemplars(self) -> Dict[str, Tuple[str, float, float]]:
        """{le-label: (trace_id, value, ts)} for buckets holding one —
        keyed the way render() labels them (`+Inf` for the overflow)."""
        with self._lock:
            exs = list(self._exemplars)
        labels = [_fmt(b) for b in self.buckets] + ["+Inf"]
        return {labels[i]: ex for i, ex in enumerate(exs) if ex is not None}

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (Prometheus
        `histogram_quantile` semantics): find the bucket the q-th
        observation falls in, linearly interpolate inside it.  This is the
        registry-fed read the fleet controller uses for its p99-vs-SLO
        signal — same cumulative counts `/metrics` exposes, so the
        autoscaler and a human watching the scrape argue from one number.
        NaN when empty; the top bucket clamps to its lower bound (the +Inf
        bucket has no upper edge to interpolate toward)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            counts = list(self._counts)
        total = sum(counts)
        if total == 0:
            return float("nan")
        rank = q * total
        cum = 0
        for i, b in enumerate(self.buckets):
            prev = cum
            cum += counts[i]
            if cum >= rank:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                if counts[i] == 0:
                    return b
                return lo + (b - lo) * (rank - prev) / counts[i]
        return self.buckets[-1]

    def render(self, exemplars: bool = False) -> str:
        with self._lock:
            counts = list(self._counts)
            total_sum = self._sum
            exs = list(self._exemplars)
        labels = [_fmt(b) for b in self.buckets] + ["+Inf"]
        lines = [self.header()]
        cum = 0
        for i, label in enumerate(labels):
            cum += counts[i]
            line = f'{self.name}_bucket{{le="{label}"}} {cum}'
            if exemplars and exs[i] is not None:
                tid, v, ts = exs[i]
                line += (f' # {{trace_id="{_escape_label(tid)}"}} '
                         f"{_fmt(v)} {_fmt(round(ts, 3))}")
            lines.append(line + "\n")
        lines.append(f"{self.name}_sum {_fmt(total_sum)}\n")
        lines.append(f"{self.name}_count {cum}\n")
        return "".join(lines)


class Registry:
    """Named metric store; `counter`/`gauge`/`histogram` are get-or-create
    (a re-request returns the SAME object, so every surface that reads a
    name reads the same numbers)."""

    def __init__(self):
        self._lock = make_lock("registry.Registry._lock")
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, *args, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def scrape(self, prefix: str = "") -> Dict[str, float]:
        """Flat numeric snapshot of every metric whose name starts with
        `prefix` — the controller-facing read of the SAME numbers
        `/metrics` renders.  Counters/gauges emit one entry per label
        combination, keyed Prometheus-style
        (``name{label="v"}`` — unlabeled series key on the bare name);
        histograms emit ``name_sum`` and ``name_count``.  Callback gauges
        are evaluated live, outside the registry lock."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)
                       if n.startswith(prefix)]
        out: Dict[str, float] = {}
        for m in metrics:
            if isinstance(m, Histogram):
                out[f"{m.name}_sum"] = m.sum
                out[f"{m.name}_count"] = float(m.count)
            else:
                for labels, v in m.samples():
                    key = m.name + _label_str(
                        m.labelnames, tuple(labels[n] for n in m.labelnames))
                    out[key] = float(v)
        return out

    def render(self, exemplars: bool = False) -> str:
        """Prometheus text exposition v0.0.4 of every registered metric;
        `exemplars=True` adds OpenMetrics exemplar suffixes to histogram
        bucket lines (off by default — plain scrapers must keep parsing)."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        return "".join(
            m.render(exemplars=exemplars) if isinstance(m, Histogram)
            else m.render() for m in metrics)


_DEFAULT = Registry()


def get_registry() -> Registry:
    """The process-default registry (trainer health gauges live here)."""
    return _DEFAULT
