"""End-to-end distributed tracing: one request's (or one train step's) life
across threads, queues, and processes as a single correlated timeline.

The PR 3 obs spine answers "how much time went WHERE, in aggregate"; this
module answers "what happened to THIS request": a `TraceContext`
(trace_id / span_id / parent_id) is created at a head (an HTTP request, a
load-generator arrival, a train step), propagated through every
cross-thread and cross-process handoff we own (the device-prefetch worker,
the batcher/scheduler queues, the fleet router, the `traceparent` HTTP
header), and every completed span lands in a bounded per-process ring as a
Chrome/Perfetto trace event. `pva-tpu-trace` (obs/tracetool.py) merges the
rings and flight records of N processes into one timeline.

Design constraints, in order (the `utils/sync.py` mold):

- **Disarmed = structurally zero overhead.** The tracer is a module global
  (`_tracer`, None by default — armed only by `obs.trace_sample_rate > 0`);
  every hot-path helper is one global read and a `None` check, returning a
  shared no-op context manager or `None`. No allocation, no lock, no id
  generation ever happens while disarmed.
- **Head-based sampling.** The sampling decision is made ONCE, where the
  trace starts (`Tracer.start`), from a seeded RNG — deterministic under a
  seed, so chaos/bench runs replay identically. Everything downstream
  (spans, queue hops, HTTP propagation) only asks "is there an active
  context?"; a continued trace (incoming `traceparent` with the sampled
  flag) is always recorded regardless of the local rate, because the head
  already decided.
- **Bounded memory.** Completed spans append to a deque ring (`maxlen`);
  a forgotten tracer can never grow without bound. Export/dump snapshots
  the ring as Chrome trace-event JSON (`ph: "X"`, wall-clock microsecond
  timestamps so multi-process merges align on one axis).
- **Self-audited overhead.** `overhead_s` = live event/start counts × a
  per-operation cost CALIBRATED at arm time (min-of-runs `perf_counter`
  micro-benchmark of the real record path — id draw, event-dict build,
  ring append — on this host; min filters preemption outliers).
  Calibration instead of per-event clocks on purpose: a per-event clock
  read costs several times the bookkeeping it would measure AND
  per-thread CPU clocks tick at jiffy granularity on this image's kernel,
  so a live audit is either the overhead or quantization noise. The
  calibrated figure excludes lock contention (bounded separately by the
  tsan gate) but counts the real work. The bench fleet lane divides it by
  run wall time and asserts `trace_overhead_frac < 0.02` — the tracing
  layer must never become the latency it exists to explain.

Stdlib-only on purpose: worker threads, the serving process, and the merge
CLI import this without jax. See docs/OBSERVABILITY.md § distributed
tracing for the runbook.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

# The armed tracer or None. Module-global by design (exactly like
# utils/sync._runtime): the disarmed check must be one load, and arming is
# a whole-process decision made at configure time.
_tracer: Optional["Tracer"] = None

TRACE_RING_DEFAULT = 4096
TRACE_FILE = "trace_ring.json"  # dump() destination under output_dir


class _Noop:
    """Shared do-nothing stand-in for every disarmed/unsampled path."""

    __slots__ = ()
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def finish(self, **tags):
        return None


NOOP = _Noop()


class TraceContext:
    """One position in a trace: (trace_id, span_id, parent_id). Immutable
    by convention; `child()` derives the next hop. Existence IS the
    sampling verdict — unsampled traces never materialize a context."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _new_span_id(), self.span_id)

    def __repr__(self) -> str:  # doctor/debug output
        return (f"TraceContext({self.trace_id[:8]}…/{self.span_id}"
                + (f"<-{self.parent_id}" if self.parent_id else "") + ")")


# id stream: urandom-SEEDED but then pure-Python getrandbits — NOT the
# seeded sampling RNG (two processes sharing a sampling seed must make the
# same DECISIONS without colliding on ids), and NOT uuid4 per id (an
# urandom syscall per span costs ~10µs on this image's kernel — an order
# of magnitude over the rest of the bookkeeping). Reseeded on pid change
# so a fork can never replay the parent's stream. C-level getrandbits is
# atomic under the GIL.
_ids = random.Random(int.from_bytes(os.urandom(16), "big"))
_ids_pid = os.getpid()


def _id_rng() -> random.Random:
    global _ids, _ids_pid
    pid = os.getpid()
    if pid != _ids_pid:
        _ids = random.Random(int.from_bytes(os.urandom(16), "big") ^ pid)
        _ids_pid = pid
    return _ids


def _new_trace_id() -> str:
    return f"{_id_rng().getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_id_rng().getrandbits(64):016x}"


# --- W3C traceparent (the HTTP hop format) ----------------------------------

def format_traceparent(ctx: TraceContext) -> str:
    """`00-<trace_id>-<span_id>-01`: version 00, sampled flag set (only
    sampled traces ever have a context to format)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(header: str) -> Optional[TraceContext]:
    """Parse an incoming `traceparent`; None for malformed/unsampled
    headers (a bad header must degrade to "untraced", never to a 500).
    The returned context's span_id is the REMOTE span — callers derive
    their local spans via `child()`."""
    try:
        parts = header.strip().split("-")
        if len(parts) != 4:
            return None
        version, trace_id, span_id, flags = parts
        int(version, 16), int(flags, 16)  # hex-validate
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        int(trace_id, 16), int(span_id, 16)
        if not int(flags, 16) & 0x01:
            return None  # head decided NOT to sample: honor it
        return TraceContext(trace_id, span_id)
    except (ValueError, AttributeError):
        return None


# --- live pieces ------------------------------------------------------------

class _Activate:
    """Push/pop an existing context on the calling thread's stack (the
    `attach` half of the capture/attach handoff pattern)."""

    __slots__ = ("_tracer", "ctx")

    def __init__(self, tracer: "Tracer", ctx: TraceContext):
        self._tracer = tracer
        self.ctx = ctx

    def __enter__(self):
        self._tracer._push(self.ctx)
        return self

    def __exit__(self, *exc):
        self._tracer._pop()
        return False


class _SpanToken:
    """One in-flight child span (obs.span integration + `trace.span`)."""

    __slots__ = ("_tracer", "ctx", "name", "_t0_wall", "_t0_perf", "_tags")

    def __init__(self, tracer: "Tracer", ctx: TraceContext, name: str,
                 tags: Optional[dict] = None):
        self._tracer = tracer
        self.ctx = ctx
        self.name = name
        self._tags = tags
        self._t0_wall = time.time()
        self._t0_perf = time.perf_counter()

    def end(self, error: bool = False, **tags) -> None:
        dur = time.perf_counter() - self._t0_perf
        self._tracer._pop()
        all_tags = dict(self._tags or {})
        all_tags.update(tags)
        if error:
            all_tags["error"] = True
        self._tracer._record(self.name, self.ctx, self._t0_wall, dur,
                             all_tags)


class _TraceSpan:
    """`with trace.span("device_dispatch", bucket=4): ...` — a child span
    under the CURRENT context (no-op handled by the module helper)."""

    __slots__ = ("_tracer", "name", "_tags", "_tok")

    def __init__(self, tracer: "Tracer", name: str, tags: dict):
        self._tracer = tracer
        self.name = name
        self._tags = tags
        self._tok: Optional[_SpanToken] = None

    def __enter__(self):
        self._tok = self._tracer.span_begin(self.name, self._tags)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._tok is not None:
            self._tok.end(error=exc_type is not None)
        return False


class TraceHandle:
    """A root (or continued) span. Two usage shapes:

    - synchronous: `with tracer.start("train_step", gstep=g) or NOOP: ...`
      — activates the context for the block, records the root event on
      exit;
    - asynchronous (the load generator, HTTP fronts): keep the handle,
      `attach(handle.ctx)` around the submit, `handle.finish(...)` when
      the future resolves. `finish` is once-only, so entering AND
      finishing cannot double-record."""

    __slots__ = ("_tracer", "ctx", "name", "_tags", "_t0_wall", "_t0_perf",
                 "_done", "_entered")

    def __init__(self, tracer: "Tracer", ctx: TraceContext, name: str,
                 tags: dict):
        self._tracer = tracer
        self.ctx = ctx
        self.name = name
        self._tags = tags
        self._t0_wall = time.time()
        self._t0_perf = time.perf_counter()
        self._done = False
        self._entered = False

    def __enter__(self):
        self._tracer._push(self.ctx)
        self._entered = True
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._pop()
        self._entered = False
        self.finish(**({"error": True} if exc_type is not None else {}))
        return False

    def finish(self, **tags) -> None:
        """Record the root event (idempotent; async completions race a
        with-exit only in caller bugs, and the first writer wins)."""
        if self._done:
            return
        self._done = True
        dur = time.perf_counter() - self._t0_perf
        all_tags = dict(self._tags)
        all_tags.update(tags)
        self._tracer._record(self.name, self.ctx, self._t0_wall, dur,
                             all_tags)


@shared_state("_events", "_started", "_sampled", "_forced", "_continued",
              "_appended", "_overhead_s", "_last_export")
class Tracer:
    """Head-sampled tracer + bounded per-process trace-event ring."""

    def __init__(self, sample_rate: float = 1.0, seed: int = 0,
                 capacity: int = TRACE_RING_DEFAULT, output_dir: str = ""):
        if not 0.0 <= float(sample_rate) <= 1.0:
            raise ValueError(
                f"trace_sample_rate must be in [0, 1], got {sample_rate}")
        self.sample_rate = float(sample_rate)
        self.seed = int(seed)
        self.output_dir = output_dir
        self._lock = make_lock("Tracer._lock")
        # seeded decision stream: deterministic under a seed (forced starts
        # and continuations deliberately do NOT consume from it)
        self._rng = random.Random(seed)
        self._events: deque = deque(maxlen=max(int(capacity), 16))
        self._tls = threading.local()
        self._started = 0    # start() calls (sampled or not)
        self._sampled = 0    # roots that got a context (incl. forced)
        self._forced = 0     # force=True roots (debug probes) among sampled
        self._continued = 0  # traces continued from a remote parent
        self._appended = 0   # events ever recorded (ring may have evicted)
        self._overhead_s = 0.0  # calibrated bookkeeping CPU-time estimate
        self._last_export = ""
        # one-time calibration of the per-event bookkeeping cost on THIS
        # host: ids + timing reads + event-dict build + bounded append —
        # the same work _record and span_begin/TraceHandle do per event.
        # Billed per live event instead of measured live (module
        # docstring's overhead note); min of repeated perf_counter runs
        # filters a preemption landing inside one calibration pass.
        tmp: deque = deque(maxlen=64)
        parent = TraceContext(_new_trace_id(), _new_span_id())
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(256):
                ctx = parent.child()
                tmp.append({
                    "name": "calibrate", "ph": "X",
                    "ts": round(time.time() * 1e6, 1),
                    "dur": round(time.perf_counter() * 1e6, 1),
                    "pid": os.getpid(), "tid": threading.get_ident(),
                    "args": {"trace_id": ctx.trace_id,
                             "span_id": ctx.span_id,
                             "parent_id": ctx.parent_id,
                             "thread": threading.current_thread().name},
                })
            best = min(best, (time.perf_counter() - t0) / 256)
        self._event_cost_s = max(best, 0.0)

    # --- per-thread context stack ----------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, ctx: TraceContext) -> None:
        self._stack().append(ctx)

    def _pop(self) -> None:
        st = self._stack()
        if st:
            st.pop()

    def current(self) -> Optional[TraceContext]:
        st = getattr(self._tls, "stack", None)
        return st[-1] if st else None

    def activate(self, ctx: TraceContext) -> _Activate:
        """Re-establish a captured context on THIS thread (the consumer
        half of a queue/thread handoff)."""
        return _Activate(self, ctx)

    # --- roots ------------------------------------------------------------

    def start(self, name: str, force: bool = False,
              **tags) -> Optional[TraceHandle]:
        """Start a new trace at a head. Returns None when the head-based
        sampler says no (callers fall back to `or NOOP`); `force=True`
        bypasses sampling for debug probes without consuming the seeded
        decision stream."""
        with self._lock:
            self._started += 1
            sampled = force or (self.sample_rate > 0.0
                                and self._rng.random() < self.sample_rate)
            if sampled:
                self._sampled += 1
                if force:
                    self._forced += 1
            # bill the head's id generation + handle allocation (below,
            # when sampled) at the calibrated per-event rate
            self._overhead_s += self._event_cost_s
        if not sampled:
            return None
        ctx = TraceContext(_new_trace_id(), _new_span_id())
        return TraceHandle(self, ctx, name, tags)

    def continue_trace(self, header, name: str,
                       **tags) -> Optional[TraceHandle]:
        """Continue a trace from an incoming `traceparent` header (or an
        explicit TraceContext). The remote head already sampled this trace,
        so the local rate is irrelevant; None only for malformed headers."""
        ctx = (header if isinstance(header, TraceContext)
               else parse_traceparent(header or ""))
        if ctx is None:
            return None
        with self._lock:
            self._continued += 1
        return TraceHandle(self, ctx.child(), name, tags)

    # --- spans ------------------------------------------------------------

    def span_begin(self, name: str,
                   tags: Optional[dict] = None) -> Optional[_SpanToken]:
        """Open a child span under the current context; None when no trace
        is active on this thread (the obs.span integration point — an
        untraced span costs exactly this check)."""
        cur = self.current()
        if cur is None:
            return None
        ctx = cur.child()
        self._push(ctx)
        return _SpanToken(self, ctx, name, tags)

    def span(self, name: str, **tags):
        """Context-manager child span (module helper `trace.span` adds the
        disarmed short-circuit)."""
        if self.current() is None:
            return NOOP
        return _TraceSpan(self, name, tags)

    def event(self, ctx: TraceContext, name: str, t0_wall: float,
              dur_s: float, **tags) -> None:
        """Record a completed child span under `ctx` with externally
        measured timing (queue waits: the producer stamped t_enqueue, the
        consumer knows the wait — no token ever lived across the hop)."""
        self._record(name, ctx.child(), t0_wall, dur_s, tags)

    # --- ring -------------------------------------------------------------

    def _record(self, name: str, ctx: TraceContext, t0_wall: float,
                dur_s: float, tags: Optional[dict]) -> None:
        args: Dict[str, object] = {
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "thread": threading.current_thread().name,
        }
        if ctx.parent_id:
            args["parent_id"] = ctx.parent_id
        if tags:
            args.update(tags)
        evt = {
            "name": name,
            "ph": "X",  # complete event: wall-clock start + duration
            "ts": round(t0_wall * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": args,
        }
        with self._lock:
            self._events.append(evt)
            self._appended += 1
            # calibrated accounting (×2: the begin side — span_begin /
            # token or handle construction with its clock reads — costs
            # about the same as this record path)
            self._overhead_s += self._event_cost_s * 2

    # --- export -----------------------------------------------------------

    def export(self) -> dict:
        """Snapshot the ring as a Chrome/Perfetto trace-event JSON dict."""
        with self._lock:
            events = list(self._events)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"pid": os.getpid(),
                          "sample_rate": self.sample_rate,
                          "seed": self.seed},
        }

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write the ring to `path` (default `<output_dir>/trace_ring.json`).
        Returns the written path, or None when there is nowhere to write or
        the write failed — the trace ring must never crash a dying process
        (the flight-recorder contract)."""
        if path is None:
            if not self.output_dir:
                return None
            path = os.path.join(self.output_dir, TRACE_FILE)
        payload = self.export()
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # tmp + rename: a reader (pva-tpu-trace, a second shell's
            # doctor) must never see a torn ring, and two processes
            # mis-configured onto one output_dir degrade to last-writer-
            # wins instead of interleaved garbage
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            return None
        with self._lock:
            self._last_export = path
        return path

    # --- introspection ----------------------------------------------------

    def overhead_s(self) -> float:
        with self._lock:
            return self._overhead_s

    def slowest(self, k: int = 5) -> List[dict]:
        """Top-k ring events by duration among ROOT spans (no parent_id) —
        the doctor's "which requests were slow" view."""
        with self._lock:
            events = list(self._events)
        roots = [e for e in events if "parent_id" not in e["args"]]
        roots.sort(key=lambda e: -e["dur"])
        return [{"trace_id": e["args"]["trace_id"], "name": e["name"],
                 "dur_ms": round(e["dur"] / 1e3, 3)} for e in roots[:k]]

    def stats(self) -> dict:
        with self._lock:
            started, sampled = self._started, self._sampled
            forced = self._forced
            continued, appended = self._continued, self._appended
            overhead, last = self._overhead_s, self._last_export
            ring_len = len(self._events)
            capacity = self._events.maxlen
        return {
            "sample_rate": self.sample_rate,
            "started": started,
            "sampled": sampled,
            "forced": forced,
            "sampled_frac": round(sampled / started, 4) if started else 0.0,
            "continued": continued,
            "events_recorded": appended,
            "ring_occupancy": ring_len,
            "ring_capacity": capacity,
            "events_evicted": max(appended - ring_len, 0),
            "overhead_s": round(overhead, 6),
            "last_export": last,
        }


# --- module API (the one-global-read hot path) ------------------------------

def get_tracer() -> Optional[Tracer]:
    return _tracer


def configure_tracing(sample_rate: float, seed: int = 0,
                      capacity: int = TRACE_RING_DEFAULT,
                      output_dir: str = "") -> Optional[Tracer]:
    """Arm (sample_rate > 0) or disarm (0) process-wide tracing — called
    from TrainConfig.obs wiring (`obs.trace_sample_rate`) and the bench
    harness, never per-request."""
    global _tracer
    if sample_rate <= 0.0:
        _tracer = None
        return None
    _tracer = Tracer(sample_rate=sample_rate, seed=seed, capacity=capacity,
                     output_dir=output_dir)
    return _tracer


def disable_tracing() -> None:
    global _tracer
    _tracer = None


def capture() -> Optional[TraceContext]:
    """The producer half of a handoff: grab the current context (or None)
    to ship alongside a queue payload / thread start. One global read when
    disarmed."""
    rt = _tracer
    return None if rt is None else rt.current()


def attach(ctx: Optional[TraceContext]):
    """The consumer half: re-establish a captured context on this thread.
    Shared no-op when disarmed or when there was nothing to carry."""
    rt = _tracer
    if rt is None or ctx is None:
        return NOOP
    return rt.activate(ctx)


def root(name: str, **tags):
    """Start-or-noop: `with trace.root("train_step", gstep=g): ...`."""
    rt = _tracer
    if rt is None:
        return NOOP
    return rt.start(name, **tags) or NOOP


def span(name: str, **tags):
    """Child-span-or-noop under the current context."""
    rt = _tracer
    if rt is None:
        return NOOP
    return rt.span(name, **tags)


def current_traceparent() -> Optional[str]:
    """The outgoing HTTP header for the current context, or None."""
    rt = _tracer
    if rt is None:
        return None
    cur = rt.current()
    return None if cur is None else format_traceparent(cur)


def dump(path: Optional[str] = None) -> Optional[str]:
    rt = _tracer
    return None if rt is None else rt.dump(path)


def snapshot() -> dict:
    """Doctor view: ring occupancy, sampled fraction, slowest traces, last
    export path (`pva-tpu-doctor` trace_snapshot)."""
    rt = _tracer
    if rt is None:
        return {"enabled": False}
    out = {"enabled": True}
    out.update(rt.stats())
    out["slowest_traces"] = rt.slowest()
    return out
