"""Unified telemetry spine: spans, flight recorder, watchdog, metrics.

The shared observability layer for every subsystem (data decode pool,
device prefetcher, pjit train loop, checkpointing, serving micro-batcher).
Stdlib-only — importable from worker threads and the serving process
without touching jax. See docs/OBSERVABILITY.md for the span taxonomy and
the runbook.

Process-default singletons (`get_collector`/`get_recorder`/`get_registry`)
are the convenient shared path — like the logging module, telemetry wants
ambient availability; tests construct private instances. `configure()` is
the one switch: `obs.enabled=false` turns every span into a shared no-op
context manager and detaches the recorder.
"""

from __future__ import annotations

from pytorchvideo_accelerate_tpu.obs.flight_recorder import (  # noqa: F401
    FlightRecorder,
    get_recorder,
)
from pytorchvideo_accelerate_tpu.obs.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
)
from pytorchvideo_accelerate_tpu.obs.spans import (  # noqa: F401
    BACKGROUND as BACKGROUND_SPANS,
    SpanCollector,
    current_stacks,
    get_collector,
    observe,
    span,
)
from pytorchvideo_accelerate_tpu.obs.watchdog import Watchdog  # noqa: F401
# distributed tracing (obs/trace.py): `obs.trace.configure_tracing(...)`,
# capture/attach handoff helpers, the per-process trace ring
from pytorchvideo_accelerate_tpu.obs import trace  # noqa: F401
# pva-tpu-hbm (PR 18): the device-memory ledger, the scrape-tick history
# ring + burn-rate alert engine, and on-demand profiler capture — all
# follow the sync.py arming discipline (disarmed = one global read)
from pytorchvideo_accelerate_tpu.obs import alerts  # noqa: F401
from pytorchvideo_accelerate_tpu.obs import history  # noqa: F401
from pytorchvideo_accelerate_tpu.obs import memory  # noqa: F401
from pytorchvideo_accelerate_tpu.obs import profiler  # noqa: F401
from pytorchvideo_accelerate_tpu.obs.alerts import (  # noqa: F401
    AlertEngine,
    AlertRule,
)
from pytorchvideo_accelerate_tpu.obs.history import MetricsHistory  # noqa: F401,E501
from pytorchvideo_accelerate_tpu.obs.memory import MemoryLedger  # noqa: F401

# default wiring: completed spans feed the flight-recorder ring
get_collector().recorder = get_recorder()


def configure(enabled: bool = None, capacity: int = None) -> SpanCollector:
    """Flip the process-default telemetry on/off and/or resize the flight
    ring (Trainer/serving call this from TrainConfig.obs)."""
    collector = get_collector()
    recorder = get_recorder()
    if capacity is not None:
        recorder.set_capacity(capacity)
    if enabled is not None:
        collector.enabled = bool(enabled)
        collector.recorder = recorder if enabled else None
    return collector
