"""Heartbeat hang watchdog: dump evidence BEFORE the external kill.

The failure mode this exists for (PROBES_r05.md, tier-1's 870s cap): a
wedged PJRT handshake, a stuck H2D copy, or a deadlocked queue leaves the
process silently idle until an external `timeout -k` kills it blind — no
stack, no timeline, nothing to diagnose. Each asynchronous component (train
loop, device prefetcher, serving batcher) pings `heartbeat(name)` whenever
it makes progress; a daemon poll thread checks ages, and the FIRST
component to exceed `timeout_s` triggers one stall dump:

- all-thread Python stacks (sys._current_frames) to stderr,
- the open span stacks (who was inside what when it froze),
- the flight-recorder ring to `<output_dir>/flight_record.json`.

The watchdog NEVER kills: it is a diagnoser, not an executioner — a false
positive (a legitimately long compile) costs one noisy dump, nothing more.
Per-component one-shot arming: a stalled name fires once, then re-arms on
its next heartbeat, so a wedged-then-recovered component can report again
while a permanently wedged one doesn't spam a dump per poll tick.
Components that finish cleanly call `clear(name)` so an idle-but-healthy
phase (between epochs, a drained prefetcher) is not a stall.

Sections (`with watchdog.section(name, detail)`) add ATTRIBUTION: while a
component is inside a section, a stall on it is reported as wedged inside
that detail string — how a straggling/wedged mesh collective (the
`parallel/hangcheck.py` collective-hang detector wraps every watched
collective in one, detail carrying the op + host index) is distinguished
from a merely slow input pipeline. Section exit CLEARS the component:
"no collective in flight" is idle, never a stall.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, Tuple

from pytorchvideo_accelerate_tpu.utils.sync import (
    make_lock,
    make_thread,
    shared_state,
)


@shared_state("stall_count", "last_stalled", "last_attribution", "_thread")
class Watchdog:
    """No-progress detector over named heartbeats."""

    def __init__(self, timeout_s: float, output_dir: str = "",
                 recorder=None, collector=None,
                 on_stall: Optional[Callable[[List[str]], None]] = None,
                 poll_s: Optional[float] = None):
        if timeout_s <= 0:
            raise ValueError(f"watchdog timeout must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.output_dir = output_dir
        self.recorder = recorder      # FlightRecorder or None
        self.collector = collector    # SpanCollector or None (open spans)
        self.on_stall = on_stall      # test/ops hook, called after the dump
        self._poll_s = poll_s or min(max(self.timeout_s / 4.0, 0.02), 5.0)
        self._lock = make_lock("Watchdog._lock")
        self._beats = {}   # name -> last monotonic heartbeat
        self._fired = set()  # names already dumped for the current stall
        self._sections: Dict[str, Tuple[str, float]] = {}  # name -> (detail, t)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stall_count = 0
        self.last_stalled: List[str] = []
        # stalled name -> (detail, seconds inside) for components that were
        # inside a section when they stalled (the collective-hang verdict)
        self.last_attribution: Dict[str, Tuple[str, float]] = {}

    # --- component side ---------------------------------------------------

    def heartbeat(self, name: str = "main") -> None:
        """Progress ping; the first ping registers the component."""
        with self._lock:
            self._beats[name] = time.monotonic()
            self._fired.discard(name)

    def beat_fn(self, name: str) -> Callable[[], None]:
        """Bound zero-arg pinger for components that take a plain callable."""
        return lambda: self.heartbeat(name)

    def clear(self, name: str) -> None:
        """Deregister a component that finished cleanly (no longer expected
        to make progress — not a stall)."""
        with self._lock:
            self._beats.pop(name, None)
            self._fired.discard(name)
            self._sections.pop(name, None)

    @contextlib.contextmanager
    def section(self, name: str, detail: str = ""):
        """Attributed progress window: heartbeat + mark `name` as inside
        `detail` on entry; a stall while open reports the detail (who is
        wedged in WHAT — a `psum` on host 3, not just "no progress").
        Exit clears the component entirely: a name that is only expected
        to progress while inside sections (a collective) is idle-healthy
        between them."""
        now = time.monotonic()
        with self._lock:
            self._beats[name] = now
            self._fired.discard(name)
            self._sections[name] = (detail, now)
        try:
            yield
        finally:
            self.clear(name)

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "Watchdog":
        thread = self._thread
        if thread is not None and thread.is_alive():
            # already polling — or a stopped poller still draining a slow
            # stall dump: never spawn a second one (duplicate dumps)
            return self
        self._stop.clear()  # a stopped watchdog can be restarted
        thread = make_thread(
            target=self._run, name="pva-watchdog", daemon=True)
        # `_thread` is handed between start()/stop() callers (trainer main
        # thread, serving close path): same lock as the beat table
        with self._lock:
            self._thread = thread
        thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=self._poll_s * 4 + 1.0)
            if not thread.is_alive():
                with self._lock:
                    if self._thread is thread:  # a racing start() may have
                        self._thread = None     # installed a fresh poller
            # else: keep the handle so start() can see the straggler

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            self.check()

    # --- detection --------------------------------------------------------

    def check(self, now: Optional[float] = None) -> List[str]:
        """One poll: returns (and dumps for) newly-stalled components.
        Public so tests can drive detection deterministically."""
        now = time.monotonic() if now is None else now
        with self._lock:
            stalled = sorted(
                name for name, t in self._beats.items()
                if name not in self._fired and now - t > self.timeout_s)
            self._fired.update(stalled)
        if stalled:
            self._fire(stalled)
        return stalled

    def _fire(self, stalled: List[str]) -> None:
        # written on the poll thread, read by tests/operators from others —
        # same lock as the beat table (pva-tpu-lint lock-discipline)
        now = time.monotonic()
        with self._lock:
            self.stall_count += 1
            self.last_stalled = list(stalled)
            attribution = {
                name: (detail, round(now - t, 3))
                for name, (detail, t) in self._sections.items()
                if name in stalled}
            self.last_attribution = attribution
        lines = [
            f"[watchdog] NO PROGRESS from {', '.join(stalled)} for "
            f"> {self.timeout_s:g}s — dumping all-thread stacks + flight "
            "record before an external timeout kills the process blind",
        ]
        for name, (detail, age) in attribution.items():
            # the collective-hang verdict: wedged INSIDE an attributed
            # operation, not merely quiet between them
            lines.append(f"[watchdog] {name} wedged inside '{detail}' "
                         f"for {age:g}s")
        if self.collector is not None:
            open_spans = self.collector.current_stacks()
            if open_spans:
                lines.append(f"[watchdog] open spans: {open_spans}")
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            lines.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
            lines.append("".join(traceback.format_stack(frame)).rstrip())
        print("\n".join(lines), file=sys.stderr, flush=True)
        if self.recorder is not None:
            self.recorder.record(
                "watchdog", "stall", stalled=list(stalled),
                timeout_s=self.timeout_s,
                **({"attribution": {n: f"{d} ({a:g}s)"
                                    for n, (d, a) in attribution.items()}}
                   if attribution else {}))
            path = None
            if self.output_dir:
                import os

                path = self.recorder.dump(
                    os.path.join(self.output_dir, "flight_record.json"))
            else:
                path = self.recorder.dump()
            if path:
                print(f"[watchdog] flight record dumped to {path}",
                      file=sys.stderr, flush=True)
        if self.on_stall is not None:
            try:
                self.on_stall(list(stalled))
            except Exception:  # the hook must not kill the poll thread
                pass
