"""Multi-window SLO burn-rate alerting over the metrics history.

One threshold on an instantaneous number either pages on every blip or
never pages at all. The SRE error-budget pattern fixes both with TWO
windows per rule: a *fast* window (catches an active burn quickly) and a
*slow* window (proves it is sustained) — the rule fires only when BOTH
windows burn past the rule's `burn` factor, and clears only after both
have been below the (lower) `clear_burn` for `hold_clear` consecutive
ticks. The asymmetric clear threshold plus the hold is the no-flap
hysteresis: one recovered tick never toggles an alert.

A rule reads one *series* out of `MetricsHistory` (the flat
`Registry.scrape()` key space):

- ``kind="gauge"``  — window mean of an instantaneous value (a p99-ms
  gauge, a depth gauge).  burn = mean / objective.
- ``kind="ratio"``  — delta(num)/delta(den) of a counter pair over the
  window (shed fraction, error fraction; also histogram _sum/_count
  pairs, giving a windowed mean). burn = ratio / objective.

`AlertEngine.tick()` drives `history.tick()` (one scrape per control
tick), evaluates every rule, publishes `pva_alert_active{rule=}` 0/1
gauges and `pva_alert_transitions_total{rule=,to=}` counters, and drops
fire/clear events into the flight ring — so the /history ring, /metrics,
and the flight recorder all tell the same story about an incident.

Arming discipline: module-level `get_engine()` is one global read;
nothing evaluates until `configure()` arms an engine. Stdlib-only.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from pytorchvideo_accelerate_tpu.obs.history import MetricsHistory
from pytorchvideo_accelerate_tpu.utils.sync import make_lock, shared_state

_DEFAULT: Optional["AlertEngine"] = None


@dataclass(frozen=True)
class AlertRule:
    """One multi-window burn-rate rule over a history series."""

    name: str
    objective: float            # the SLO: "p99 <= 80ms" -> 80.0
    key: str = ""               # gauge kind: the flat scrape key
    num: str = ""               # ratio kind: counter-pair keys
    den: str = ""
    kind: str = "gauge"         # "gauge" | "ratio"
    fast_s: float = 60.0
    slow_s: float = 300.0
    burn: float = 1.0           # fire when BOTH windows >= burn
    clear_burn: float = 0.9     # clear only below this (hysteresis)
    hold_clear: int = 2         # ...for this many consecutive ticks

    def __post_init__(self):
        if self.kind not in ("gauge", "ratio"):
            raise ValueError(f"unknown rule kind {self.kind!r}")
        if self.objective <= 0:
            raise ValueError("objective must be positive")
        if self.fast_s >= self.slow_s:
            raise ValueError("fast window must be shorter than slow")
        if self.clear_burn > self.burn:
            raise ValueError("clear_burn above burn would flap by design")

    def _read(self, history: MetricsHistory, window_s: float,
              now: float) -> Optional[float]:
        if self.kind == "gauge":
            return history.window_mean(self.key, window_s, now=now)
        return history.ratio(self.num, self.den, window_s, now=now)

    def burn_rates(self, history: MetricsHistory,
                   now: float) -> Dict[str, Optional[float]]:
        """{"fast": x, "slow": y} burn factors (value/objective); None
        where the window holds no data — an empty window never burns."""
        out = {}
        for label, win in (("fast", self.fast_s), ("slow", self.slow_s)):
            v = self._read(history, win, now)
            out[label] = None if v is None else v / self.objective
        return out


@dataclass
class _RuleState:
    active: bool = False
    since: float = 0.0
    clear_streak: int = 0
    fires: int = 0
    last_burn: Dict[str, Optional[float]] = field(default_factory=dict)
    cleared_at: Optional[float] = None


@shared_state("_state")
class AlertEngine:
    """Evaluates the rule set each tick; ticks race snapshot readers
    (the doctor, /history handlers) and the tsan stress leg's flap."""

    def __init__(self, history: MetricsHistory,
                 rules: List[AlertRule], registry=None, recorder=None):
        from pytorchvideo_accelerate_tpu.obs.registry import get_registry

        names = [r.name for r in rules]
        if len(names) != len(set(names)):
            raise ValueError("duplicate rule names")
        self._lock = make_lock("obs.AlertEngine._lock")
        self.history = history
        self.rules = list(rules)
        self.registry = registry if registry is not None else get_registry()
        self.recorder = recorder
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules}
        self._g_active = self.registry.gauge(
            "pva_alert_active", "1 while the burn-rate rule is firing",
            labelnames=("rule",))
        self._c_transitions = self.registry.counter(
            "pva_alert_transitions_total",
            "fire/clear transitions per rule (a fire is ONE transition "
            "however long the burn lasts — the flap detector)",
            labelnames=("rule", "to"))
        for r in self.rules:
            self._g_active.set(0, rule=r.name)

    def tick(self, now: Optional[float] = None) -> List[str]:
        """One control tick: scrape into the history, evaluate every
        rule, publish transitions. Returns currently-active rule names."""
        ts = time.time() if now is None else float(now)
        self.history.tick(now=ts)
        active: List[str] = []
        for rule in self.rules:
            burns = rule.burn_rates(self.history, ts)
            burning = all(b is not None and b >= rule.burn
                          for b in burns.values())
            calm = all(b is None or b < rule.clear_burn
                       for b in burns.values())
            with self._lock:
                st = self._state[rule.name]
                st.last_burn = burns
                fired = cleared = False
                if not st.active and burning:
                    st.active, st.since = True, ts
                    st.clear_streak = 0
                    st.fires += 1
                    fired = True
                elif st.active:
                    # hysteresis: clear_burn is below burn AND the calm
                    # must hold for hold_clear consecutive ticks
                    st.clear_streak = st.clear_streak + 1 if calm else 0
                    if st.clear_streak >= rule.hold_clear:
                        st.active = False
                        st.cleared_at = ts
                        cleared = True
                is_active = st.active
            if fired:
                self._g_active.set(1, rule=rule.name)
                self._c_transitions.inc(rule=rule.name, to="firing")
                if self.recorder is not None:
                    self.recorder.warn(
                        f"alert firing: {rule.name}", rule=rule.name,
                        fast_burn=burns.get("fast"),
                        slow_burn=burns.get("slow"),
                        objective=rule.objective)
            elif cleared:
                self._g_active.set(0, rule=rule.name)
                self._c_transitions.inc(rule=rule.name, to="clear")
                if self.recorder is not None:
                    self.recorder.record(
                        "alert", "clear", rule=rule.name,
                        active_s=round(ts - self._state[rule.name].since, 3))
            if is_active:
                active.append(rule.name)
        return active

    def active(self) -> List[str]:
        with self._lock:
            return sorted(n for n, st in self._state.items() if st.active)

    def fires(self, rule: str) -> int:
        with self._lock:
            return self._state[rule].fires

    def snapshot(self) -> Dict:
        """Doctor-facing: history occupancy plus per-rule state (active,
        fire count, last burn factors, last clear)."""
        with self._lock:
            rules = {
                n: {"active": st.active, "fires": st.fires,
                    "since": st.since if st.active else None,
                    "cleared_at": st.cleared_at,
                    "last_burn": dict(st.last_burn)}
                for n, st in self._state.items()}
        return {"history": self.history.snapshot(), "rules": rules,
                "active": sorted(n for n, r in rules.items() if r["active"])}


def default_rules() -> List[AlertRule]:
    """The shipped serving-SLO rule set (docs/OBSERVABILITY.md § authoring
    a rule): p99 latency, shed fraction, error fraction — the three
    series the fleet controller already steers on."""
    return [
        # windowed mean latency via the histogram's _sum/_count pair
        # (serving/stats.py names); label-summed series (history.series)
        # let the shed rule read across its {state=} variants
        AlertRule(name="serve_latency_burn", kind="ratio",
                  num="pva_serving_request_latency_seconds_sum",
                  den="pva_serving_request_latency_seconds_count",
                  objective=0.080, fast_s=30.0, slow_s=120.0),
        AlertRule(name="shed_burn", kind="ratio",
                  num="pva_serving_shed_total",
                  den="pva_serving_requests_total",
                  objective=0.05, fast_s=30.0, slow_s=120.0),
        AlertRule(name="error_burn", kind="ratio",
                  num="pva_serving_errors_total",
                  den="pva_serving_requests_total",
                  objective=0.01, fast_s=30.0, slow_s=120.0),
    ]


def get_engine() -> Optional[AlertEngine]:
    return _DEFAULT


def configure(enabled: bool = True, history: Optional[MetricsHistory] = None,
              rules: Optional[List[AlertRule]] = None,
              **kwargs) -> Optional[AlertEngine]:
    """Arm (or disarm) the process-default alert engine (building a
    history ring too when none is supplied)."""
    global _DEFAULT
    if not enabled:
        _DEFAULT = None
        return None
    if history is None:
        from pytorchvideo_accelerate_tpu.obs import history as history_mod

        history = history_mod.get_history() or history_mod.configure()
    _DEFAULT = AlertEngine(history, rules or default_rules(), **kwargs)
    return _DEFAULT
