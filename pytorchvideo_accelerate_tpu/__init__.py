"""TPU-native video action-recognition training framework.

A from-scratch JAX/XLA re-design of the capability surface of
``nateraw/pytorchvideo-accelerate`` (reference: ``/root/reference/run.py``):
distributed training of video models (SlowFast, Slow-R50, X3D, MViT, VideoMAE)
on Kinetics-style datasets.

Design stance (see SURVEY.md §7): instead of the reference's
Accelerator-object mutation API (``prepare``/``backward``/``gather``), the
framework is built around an explicit state pytree, pure compiled step
functions, and sharding declared on a ``jax.sharding.Mesh``:

- ``Accelerator.prepare``      -> mesh construction + NamedSharding rules
  (``parallel.mesh``, ``parallel.sharding``)
- ``accelerator.backward``+DDP -> ``jax.value_and_grad`` inside a jitted step;
  the gradient all-reduce is implied by sharded autodiff (``trainer.steps``)
- AMP GradScaler               -> bf16 compute / fp32 params, no loss scaling
- ``accelerator.save_state``   -> orbax checkpointing (``trainer.checkpoint``)
- tracker multiplexer          -> host-0 writers (``trainer.tracking``)
- ``accelerate launch``        -> per-host runner + ``jax.distributed``
  (``parallel.distributed``, ``launch.py``)
"""

__version__ = "0.4.0"  # keep in sync with pyproject.toml

from pytorchvideo_accelerate_tpu.config import (  # noqa: F401
    CheckpointConfig,
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    TrackingConfig,
    TrainConfig,
)
