"""Clip samplers: pick a [start, end) time window from a video.

Reference semantics (run.py:154,163: `make_clip_sampler("random"|"uniform",
clip_duration)` [external pytorchvideo]):

- "random" (train): uniformly-random start in [0, duration - clip_duration].
- "uniform" (val): the reference's uniform sampler tiles the video into
  consecutive clips, but wrapped in `LimitDataset` (run.py:25-35) only the
  first `num_videos` clips of the stream are consumed per epoch — so long
  videos shadow later ones (SURVEY §2.1 quirk). Consciously fixed here: val
  yields `num_clips` evenly-spaced clips *per video* (default 1, the
  standard single-clip eval; multi-clip eval = num_clips>1), deterministic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Tuple

import numpy as np


@dataclass(frozen=True)
class ClipSpan:
    start: float  # seconds
    end: float


def random_clip(duration: float, clip_duration: float, rng: np.random.Generator) -> ClipSpan:
    if duration <= clip_duration:
        return ClipSpan(0.0, min(clip_duration, duration))
    start = float(rng.uniform(0.0, duration - clip_duration))
    return ClipSpan(start, start + clip_duration)


def uniform_clips(duration: float, clip_duration: float, num_clips: int = 1) -> List[ClipSpan]:
    """`num_clips` evenly-spaced windows; centers for the degenerate cases."""
    if duration <= clip_duration:
        return [ClipSpan(0.0, min(clip_duration, duration))] * num_clips
    if num_clips == 1:
        starts = [(duration - clip_duration) / 2.0]
    else:
        starts = np.linspace(0.0, duration - clip_duration, num_clips).tolist()
    return [ClipSpan(float(s), float(s) + clip_duration) for s in starts]
