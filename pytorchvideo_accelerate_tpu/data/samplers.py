"""Clip samplers: pick a [start, end) time window from a video.

Reference semantics (run.py:154,163: `make_clip_sampler("random"|"uniform",
clip_duration)` [external pytorchvideo]):

- "random" (train): uniformly-random start in [0, duration - clip_duration].
- "uniform" (val): the reference's uniform sampler tiles the video into
  consecutive clips, but wrapped in `LimitDataset` (run.py:25-35) only the
  first `num_videos` clips of the stream are consumed per epoch — so long
  videos shadow later ones (SURVEY §2.1 quirk). Consciously fixed here: val
  yields `num_clips` evenly-spaced clips *per video* (default 1, the
  standard single-clip eval; multi-clip eval = num_clips>1), deterministic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Tuple

import numpy as np


@dataclass(frozen=True)
class ClipSpan:
    start: float  # seconds
    end: float


def random_clip(duration: float, clip_duration: float, rng: np.random.Generator) -> ClipSpan:
    if duration <= clip_duration:
        return ClipSpan(0.0, min(clip_duration, duration))
    start = float(rng.uniform(0.0, duration - clip_duration))
    return ClipSpan(start, start + clip_duration)


def uniform_clips(duration: float, clip_duration: float, num_clips: int = 1) -> List[ClipSpan]:
    """`num_clips` evenly-spaced windows; centers for the degenerate cases."""
    if duration <= clip_duration:
        return [ClipSpan(0.0, min(clip_duration, duration))] * num_clips
    if num_clips == 1:
        starts = [(duration - clip_duration) / 2.0]
    else:
        starts = np.linspace(0.0, duration - clip_duration, num_clips).tolist()
    return [ClipSpan(float(s), float(s) + clip_duration) for s in starts]


def substitute_indices(indices: np.ndarray, excluded, num_total: int,
                       seed: int, epoch: int) -> np.ndarray:
    """Remap quarantined sample indices onto clean ones, deterministically.

    The sampler-side half of the bad-sample quarantine
    (`data/manifest.py Quarantine`): a quarantined clip must never reach
    the decode pool, but dropping its index would change the epoch's
    batch count mid-run (steps_per_epoch feeds the LR schedule and the
    checkpointed loader position). So each excluded index is REPLACED by
    a clean index drawn from its own `(seed, 0xC1EA, epoch, index)` RNG
    stream — reproducible across restarts and independent of how many
    other clips are quarantined, matching the attempt-keyed substitution
    discipline in `pipeline.VideoClipSource.get`.

    `excluded` is a set of sample indices; `num_total` the source length.
    Returns a copy (never mutates); all-excluded degenerates to the
    original indices (nothing clean to substitute — the per-sample
    failure path then reports the real error).
    """
    excluded = set(int(i) for i in excluded)
    if not excluded:
        return indices
    clean = np.array([i for i in range(num_total) if i not in excluded],
                     dtype=indices.dtype if indices.size else np.int64)
    if clean.size == 0:
        return indices
    out = indices.copy()
    for pos in np.nonzero(np.isin(indices, list(excluded)))[0]:
        rng = np.random.default_rng(
            (seed, 0xC1EA, epoch, int(indices[pos])))
        out[pos] = clean[int(rng.integers(0, clean.size))]
    return out
