"""Data pipeline: manifest indexing, clip sampling, decode, transforms,
and the host-side loader feeding sharded clip batches to the mesh.

TPU-native replacement for the reference's L3 stack (SURVEY §2.1 R6-R11):
pytorchvideo `Kinetics` + PyAV decode + torch DataLoader workers become a
manifest scanner, cv2 (bundled FFmpeg) decode, numpy transform stack, and a
grain/threaded prefetch pipeline with per-host sharding and checkpointable
iterator state.
"""

# NOTE: data.device_prefetch is intentionally NOT re-exported here — it
# imports jax (via parallel.sharding), and this package init must stay
# importable by host-only code paths (forked decode workers, offline cache
# builds). Import it as `from ...data.device_prefetch import DevicePrefetcher`.
from pytorchvideo_accelerate_tpu.data.transforms import (  # noqa: F401
    make_transform,
    pack_pathway,
    uniform_temporal_subsample,
)
