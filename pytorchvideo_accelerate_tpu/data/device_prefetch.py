"""Device-side batch prefetch: overlap host→HBM transfer with compute.

The reference stack hides host→device latency inside torch's pinned-memory
DataLoader + DDP machinery; the TPU-native rewrite owns that slice here. A
`DevicePrefetcher` sits between the host `ClipLoader` and the step loop: a
background thread advances `ClipLoader.epoch_items()`, places each numpy
batch on the mesh (`parallel.sharding.shard_batch` — cached `NamedSharding`,
`device_put` single-process / `make_array_from_process_local_data`
multi-host), and holds a bounded ring of at most `depth` on-device batches,
so the H2D copy of batch N+1 (tens of MB of video at reference geometry)
runs while the accelerator computes batch N. Without it, every step pays the
full PCIe/host-link transfer synchronously between dispatches — the
first-order throughput lever on TPU is simply never letting the chip wait on
the host (Podracer; "Scalable Training of LMs with pjit and TPUv4").

Contracts, in order of importance:

- **Exact batch order.** The queue is strictly FIFO from a single producer;
  the consumer sees precisely the sequence `ClipLoader.epoch()` would yield.
- **LoaderState resume semantics.** `epoch_items()` never mutates
  `loader.state`; each batch carries its post-consumption `LoaderState`, and
  the prefetcher assigns it back to the loader only when the trainer takes
  the batch. A mid-epoch checkpoint therefore records the *consumed*
  position, never a position several prefetched batches ahead (which would
  make resume silently skip data).
- **Bounded residency.** A counting semaphore caps placed-but-unconsumed
  batches at `depth`: HBM cost is `depth` extra batches, never "however far
  the host got ahead".
- **Deterministic shutdown.** Early `break` (limit_train_batches), an
  exception in the step loop, or generator close all reach the same
  `finally`: stop flag set, worker joined, source generator closed (which
  cancels the host loader's in-flight decode futures). Worker-side
  exceptions cross the queue and re-raise in the consumer.
- **Observability.** Per-epoch time the consumer spent blocked waiting for
  the next device batch accumulates into `wait_s`; `pop_wait()` drains it.
  The trainer divides by the epoch's train-section wall time to report
  `input_wait_frac` (≪ 1 proves the overlap is real; → 1 means the input
  pipeline, not the model, bounds throughput).

`depth=0` degrades to synchronous inline placement (the pre-prefetch
behavior) while keeping the same interface and wait accounting — the A/B
lever, and the fallback if a backend misbehaves under threaded `device_put`.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterator, Optional

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.obs import memory as obs_memory
from pytorchvideo_accelerate_tpu.obs import trace
from pytorchvideo_accelerate_tpu.data.pipeline import ClipLoader
from pytorchvideo_accelerate_tpu.parallel.sharding import shard_batch
from pytorchvideo_accelerate_tpu.reliability.faults import fault_point
from pytorchvideo_accelerate_tpu.utils.sync import (
    make_lock,
    make_queue,
    make_thread,
    shared_state,
)

_SENTINEL_POLL_S = 0.05  # stop-flag poll cadence for blocking waits
_JOIN_TIMEOUT_S = 10.0


@shared_state("wait_s", "_resident", "max_resident")
class DevicePrefetcher:
    """Bounded background H2D pipeline over one `ClipLoader`.

    One instance per loader (train and val each get their own); `epoch()`
    mirrors `ClipLoader.epoch()`'s signature so the step loop swaps in
    without other changes, but yields mesh-placed device batches.
    """

    def __init__(
        self,
        loader: ClipLoader,
        mesh: Any,
        depth: int = 2,
        micro_dim: bool = False,
        watchdog: Optional[Any] = None,
        watchdog_name: str = "prefetch",
        wait_name: str = "input_wait",
        h2d_name: str = "h2d",
    ):
        if depth < 0:
            raise ValueError(f"device prefetch depth must be >= 0, got {depth}")
        self.loader = loader
        self.mesh = mesh
        self.depth = depth
        self.micro_dim = micro_dim
        self.wait_s = 0.0  # consumer time blocked on the next device batch
        # telemetry spine (obs/): the consumer wait doubles as the
        # `wait_name` span ("input_wait" train / "eval_input_wait" val — the
        # latter nests inside the "eval" span, so it is background-classed
        # to keep window sums single-counted); worker-side placement is the
        # `h2d_name` span ("h2d" train / "eval_h2d" val, kept apart so the
        # per-train-step obs_h2d_s never counts eval placements); the
        # worker pings the watchdog per placed batch and deregisters when
        # the epoch generator closes (idle != stalled).
        self.watchdog = watchdog
        self.watchdog_name = watchdog_name
        self.wait_name = wait_name
        self.h2d_name = h2d_name
        self._lock = make_lock("DevicePrefetcher._lock")
        self._resident = 0  # placed-but-unconsumed device batches
        self.max_resident = 0  # high-water mark (tests; monotonic per run)
        # pva-tpu-hbm: ledger component for the ring's HBM residency —
        # MEASURED placed-batch bytes (register on enqueue, release on
        # consumption/drain), never a depth×estimate. wait_name keys the
        # component so train/val prefetchers account separately.
        self._mem_component = f"prefetch_ring:{self.wait_name}"

    # --- observability ----------------------------------------------------

    def pop_wait(self) -> float:
        """Accumulated input-wait seconds since the last call (epoch-scoped
        accounting in the trainer)."""
        w, self.wait_s = self.wait_s, 0.0
        return w

    # --- placement --------------------------------------------------------

    def _place(self, batch: dict) -> Any:
        # chaos hook: "delay" here IS the slow-worker scenario (a starved
        # host link); "raise" crosses the queue and re-raises in the
        # consumer like any real placement failure. Disarmed: one global
        # read (reliability/faults.py).
        fault_point("prefetch.h2d")
        with obs.span(self.h2d_name):
            return shard_batch(self.mesh, batch, micro_dim=self.micro_dim)

    # --- iteration --------------------------------------------------------

    def epoch(self, epoch: Optional[int] = None,
              from_start: bool = False) -> Iterator[Any]:
        """Yield device-placed batches for one epoch, prefetched `depth`
        ahead; `loader.state` tracks the consumed position exactly as the
        plain host iteration would."""
        if self.depth == 0:
            yield from self._epoch_sync(epoch, from_start)
            return

        q: "queue.Queue[tuple]" = make_queue()  # bounded by `slots`, not maxsize
        stop = threading.Event()
        slots = threading.Semaphore(self.depth)
        items = self.loader.epoch_items(epoch, from_start)
        # trace handoff (obs/trace.py): capture the consumer's context so
        # the worker's h2d spans join whatever trace was active when the
        # epoch started (disarmed: one global read, ctx stays None)
        worker = make_thread(
            target=self._worker,
            args=(items, q, stop, slots, trace.capture()),
            name="device-prefetch", daemon=True,
        )
        worker.start()
        try:
            while True:
                t0 = time.perf_counter()
                kind, payload, state = q.get()
                dt = time.perf_counter() - t0
                self.wait_s += dt
                obs.observe(self.wait_name, dt)
                if kind == "batch":
                    with self._lock:
                        self._resident -= 1
                    slots.release()
                    # ownership transfers to the step loop: the ring's
                    # residency accounting drops the batch here
                    obs_memory.release(self._mem_component,
                                       obs_memory.tree_nbytes(payload))
                    self.loader.state = state
                    yield payload
                elif kind == "state":  # epoch rollover marker
                    self.loader.state = state
                elif kind == "error":
                    raise payload
                else:  # "done"
                    return
        finally:
            stop.set()
            worker.join(timeout=_JOIN_TIMEOUT_S)
            # drop queued device batches so their HBM frees promptly
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            with self._lock:
                self._resident = 0
            # drained batches free on the floor above; zero the component so
            # a worker that out-raced the drain can't leave phantom bytes
            obs_memory.release(self._mem_component)

    def _epoch_sync(self, epoch: Optional[int],
                    from_start: bool) -> Iterator[Any]:
        """depth=0: inline blocking placement (the A/B baseline). The wait
        metric keeps its meaning — time the step loop spends blocked getting
        the next batch onto the device — so input_wait_frac stays comparable
        across modes."""
        try:
            for batch, state in self.loader.epoch_items(epoch, from_start):
                if batch is None:
                    self.loader.state = state
                    continue
                t0 = time.perf_counter()
                placed = self._place(batch)
                dt = time.perf_counter() - t0
                self.wait_s += dt
                obs.observe(self.wait_name, dt)
                if self.watchdog is not None:
                    self.watchdog.heartbeat(self.watchdog_name)
                self.loader.state = state
                yield placed
        finally:
            # mirror the threaded worker: a finished epoch is idle, not
            # stalled — a stale beat would false-fire every inter-epoch gap
            if self.watchdog is not None:
                self.watchdog.clear(self.watchdog_name)

    def _worker(self, items: Iterator[tuple], q: "queue.Queue[tuple]",
                stop: threading.Event, slots: threading.Semaphore,
                ctx=None) -> None:
        """Producer: advance the host loader, place on device, enqueue.

        `ctx` is the consumer's captured trace context (trace.attach
        re-establishes it here so worker-side h2d spans join the trace).

        Every exit path funnels through `finally: items.close()` — closing
        the `epoch_items` generator from THIS thread (the only one that ever
        ran it) fires its `finally`, cancelling the host loader's pending
        decode futures; a cross-thread close would race "generator already
        executing"."""
        try:
            with trace.attach(ctx):
                for batch, state in items:
                    if self.watchdog is not None:
                        self.watchdog.heartbeat(self.watchdog_name)
                    if batch is None:  # exhaustion marker: no slot/placement
                        q.put(("state", None, state))
                        continue
                    while not stop.is_set():
                        if slots.acquire(timeout=_SENTINEL_POLL_S):
                            break
                    else:
                        return  # consumer gone; slot never acquired
                    if stop.is_set():
                        slots.release()
                        return
                    with self._lock:
                        self._resident += 1
                        self.max_resident = max(self.max_resident,
                                                self._resident)
                    placed = self._place(batch)
                    # ledger: measured bytes of the batch actually resident
                    # in the ring (released when the consumer takes it)
                    obs_memory.register(self._mem_component,
                                        obs_memory.tree_nbytes(placed))
                    q.put(("batch", placed, state))
        except BaseException as e:  # noqa: BLE001 - must cross the thread
            q.put(("error", e, None))
        else:
            q.put(("done", None, None))
        finally:
            # a finished/closed worker is idle, not stalled — stop the
            # watchdog from treating its silence as a hang
            if self.watchdog is not None:
                self.watchdog.clear(self.watchdog_name)
            items.close()
