"""Host-side clip pipeline: sources, sharded batching, prefetch, state.

TPU-native replacement for the reference's loader stack (SURVEY §2.1
R8-R10, §2.2-A4): `Kinetics` iterable dataset + `LimitDataset` + torch
`DataLoader(num_workers=8, pin_memory)` + accelerate's `BatchSamplerShard`
become:

- a `ClipSource` (real videos via manifest+cv2, or synthetic fixture),
- deterministic per-epoch shuffling from the shared seed (identical on all
  hosts — no cross-rank RNG sync needed, SURVEY A11),
- per-host index interleaving `idx[process_index::process_count]` (the
  `DistributedSampler`/`BatchSamplerShard` equivalent, without padding
  duplicates: val tail batches carry an explicit mask instead),
- a thread-pool decode pool (cv2 releases the GIL; threads give native
  decode parallelism without fork overhead) with one-batch-ahead prefetch
  (`DataLoaderShard.__iter__` prefetch semantics, data_loader.py:576-610),
- checkpointable iterator state {epoch, position} (extends checkpoint
  capability A8 to data, replacing the reference's skip-batches resume at
  run.py:246-249 with an O(1) index fast-forward).

Conscious fixes of catalogued reference quirks (SURVEY §2.1): the reference's
`LimitDataset` shares one iterator across epochs and workers (duplicated
streams, shuffle=True shuffles nothing); here every (epoch, index) maps to an
independent deterministic sample.
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from queue import Empty, Queue
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

from pytorchvideo_accelerate_tpu import obs
from pytorchvideo_accelerate_tpu.reliability.retry import retry_call
from pytorchvideo_accelerate_tpu.utils.sync import make_lock
from pytorchvideo_accelerate_tpu.data import decode as decode_mod
from pytorchvideo_accelerate_tpu.data.manifest import Manifest, Quarantine
from pytorchvideo_accelerate_tpu.data.samplers import (
    random_clip,
    substitute_indices,
    uniform_clips,
)

logger = logging.getLogger(__name__)


class _DecodeFailure(Exception):
    """Tag for decode-layer failures crossing the transform boundary —
    keeps VideoClipSource's substitution from swallowing transform bugs."""


class ClipSource:
    """A deterministic map (epoch, index) -> sample dict of numpy arrays."""

    num_classes: int

    def __len__(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def get(self, index: int, epoch: int) -> Dict[str, np.ndarray]:  # pragma: no cover
        raise NotImplementedError


def sample_views(read_span: Callable, transform: Callable, duration: float,
                 clip_duration: float, training: bool,
                 rng: np.random.Generator, num_clips: int) -> Dict[str, np.ndarray]:
    """Shared span-selection + multi-view stacking for every clip source.

    Train: ONE random span. Eval: `num_clips` evenly-spaced spans — times
    the transform's `num_spatial_crops` when it declares one (the papers'
    30-view protocol: 10 temporal x 3 spatial) — each transformed and
    stacked on ONE leading view axis, temporal-major (the eval step
    view-averages the logits; reference uniform tiling, run.py:163).
    `read_span(start_sec, end_sec) -> (T, H, W, 3) uint8`.
    """
    # training transforms can't carry spatial crops (make_transform forbids
    # it), so the attribute alone decides — this also serves sources that
    # use train-style random spans with an eval transform (SyntheticClipSource
    # at num_clips=1)
    n_spatial = max(getattr(transform, "num_spatial_crops", 1), 1)
    if training:
        spans = [random_clip(duration, clip_duration, rng)]
    else:
        spans = uniform_clips(duration, clip_duration, num_clips)
    if n_spatial > 1:
        # decode AND pre-crop once per span; spatial_views applies the
        # n_spatial crops to the shared scaled frames
        views = []
        for s in spans:
            views.extend(transform.spatial_views(read_span(s.start, s.end)))
    else:
        views = [transform(read_span(s.start, s.end), rng) for s in spans]
    if len(views) == 1:  # no view axis for the single-view case
        return views[0]
    return {k: np.stack([v[k] for v in views]) for k in views[0]}


class VideoClipSource(ClipSource):
    """Real videos: manifest entry -> clip span -> cv2 decode -> transform.

    `training=True` samples a random span with an RNG derived from
    (seed, epoch, index) — reproducible across restarts, distinct across
    epochs (what the reference's shared-iterator design failed to provide).

    Unreadable/corrupt videos (real Kinetics trees always have some) are
    substituted, not fatal: up to `_MAX_CONSECUTIVE_FAILURES` replacement
    indices, each drawn from its own attempt-keyed RNG stream
    ((seed, 0xBAD, epoch, index, attempt)) so the substitution is
    reproducible across restarts regardless of how many draws a failed
    decode consumed or whether a known-bad path was skipped outright;
    failed paths are remembered and a warning logged once per file.
    Mirrors pytorchvideo LabeledVideoDataset's retry semantics (the
    reference's decode-failure behavior, run.py:151-168 [external]); the
    label always comes from the video actually decoded. Only DECODE
    failures substitute — transform errors propagate (a transform bug must
    not silently skew the data distribution).

    With a `quarantine` (`data/manifest.Quarantine`), every exhausted-retry
    failure also counts against that clip's persisted failure budget;
    past it the path is quarantined — excluded at the SAMPLER level
    (`quarantined_indices()` feeds `samplers.substitute_indices`, so the
    clip never reaches the decode pool again, this run or the next) —
    instead of paying the retry + substitution dance every epoch or, after
    `_MAX_CONSECUTIVE_FAILURES`, raising through and killing the run.
    """

    def __init__(
        self,
        manifest: Manifest,
        transform: Callable,
        clip_duration: float,
        training: bool,
        seed: int = 42,
        num_clips: int = 1,
        decode_retries: int = 2,
        retry_base_delay_s: float = 0.05,
        quarantine: Optional[Quarantine] = None,
    ):
        self.manifest = manifest
        self.transform = transform
        self.clip_duration = clip_duration
        self.training = training
        self.seed = seed
        # total decode attempts per read before substitution: transient
        # I/O (cold NFS, flaky storage) recovers via reliability/retry.py;
        # a genuinely corrupt file still exhausts the budget fast and
        # falls through to the substitution path below
        self.decode_retries = max(int(decode_retries), 1)
        self.retry_base_delay_s = retry_base_delay_s
        # eval-only multi-view: `num_clips` evenly-spaced views per video,
        # stacked on a leading axis; the eval step view-averages the logits
        # in-graph (reference uniform-sampler tiling, run.py:163)
        self.num_clips = max(num_clips, 1) if not training else 1
        self.num_classes = manifest.num_classes
        self.quarantine = quarantine
        self._meta_cache: Dict[str, decode_mod.VideoMeta] = {}
        self._meta_lock = make_lock("VideoClipSource._meta_lock")
        self._failed: set = set()

    _MAX_CONSECUTIVE_FAILURES = 10  # pytorchvideo LabeledVideoDataset parity

    def __len__(self) -> int:
        return len(self.manifest)

    def quarantined_indices(self) -> set:
        """Manifest indices of quarantined paths — the sampler-exclusion
        input (`ClipLoader._epoch_indices` remaps them onto clean clips
        via `samplers.substitute_indices`). Empty without a quarantine."""
        if self.quarantine is None or len(self.quarantine) == 0:
            return set()
        bad = self.quarantine.paths()
        return {i for i, e in enumerate(self.manifest.entries)
                if e.path in bad}

    def _meta(self, path: str) -> decode_mod.VideoMeta:
        with self._meta_lock:
            meta = self._meta_cache.get(path)
        if meta is None:
            meta = decode_mod.probe(path)
            with self._meta_lock:
                self._meta_cache[path] = meta
        return meta

    def get(self, index: int, epoch: int) -> Dict[str, np.ndarray]:
        idx = index
        for attempt in range(self._MAX_CONSECUTIVE_FAILURES):
            # each attempt gets its OWN rng stream: reproducibility across
            # restarts must not depend on how many draws a previous attempt
            # consumed before failing, nor on whether a known-bad path was
            # skipped without any decode attempt (self._failed is run-local
            # history; attempt-keyed streams make it invisible to sampling)
            rng = (np.random.default_rng((self.seed, epoch, index))
                   if attempt == 0
                   else np.random.default_rng(
                       (self.seed, epoch, index, attempt)))
            entry = self.manifest.entries[idx]
            with self._meta_lock:
                known_bad = entry.path in self._failed
            if not known_bad and self.quarantine is not None:
                # quarantined clips are skipped without a decode attempt;
                # normally the sampler already excluded them, this covers
                # direct get() callers and just-quarantined paths mid-epoch
                known_bad = self.quarantine.contains(entry.path)
            if not known_bad:
                # only DECODE failures are substitutable; the read_span
                # wrapper tags them so a transform bug raising ValueError
                # inside sample_views can't be mistaken for a corrupt file
                # (which would silently blacklist readable videos)
                def read_span(a, b, _path=entry.path):
                    try:
                        # transient read failures retry with backoff before
                        # the substitution machinery gives up on the file
                        return retry_call(
                            lambda: decode_mod.decode_span(_path, a, b),
                            name="decode.read",
                            attempts=self.decode_retries,
                            retry_on=decode_mod.DECODE_ERRORS,
                            base_delay_s=self.retry_base_delay_s,
                            deadline_s=5.0,
                        )
                    except decode_mod.DECODE_ERRORS as e:
                        raise _DecodeFailure(str(e)) from e

                def mark_failed(e):
                    with self._meta_lock:
                        self._failed.add(entry.path)
                    if self.quarantine is not None:
                        # one exhausted-retry failure against the persisted
                        # budget; crossing it sidelines the clip for good
                        self.quarantine.record(entry.path, e)
                    logger.warning(
                        "skipping unreadable video %s (%s: %s); substituting",
                        entry.path, type(e).__name__, e)

                try:
                    meta = self._meta(entry.path)
                except decode_mod.DECODE_ERRORS as e:
                    mark_failed(e)
                else:
                    try:
                        out = sample_views(
                            read_span, self.transform, meta.duration,
                            self.clip_duration, self.training, rng,
                            self.num_clips,
                        )
                    except _DecodeFailure as e:
                        mark_failed(e)
                    else:
                        out["label"] = np.int32(entry.label)
                        return out
            # deterministic replacement, also attempt-keyed
            idx = int(np.random.default_rng(
                (self.seed, 0xBAD, epoch, index, attempt)
            ).integers(0, len(self.manifest)))
        raise IOError(
            f"{self._MAX_CONSECUTIVE_FAILURES} consecutive unreadable videos "
            f"starting at index {index} (see warnings for paths)")


class SyntheticClipSource(ClipSource):
    """Label-coded synthetic clips — the `RegressionDataset` moral equivalent
    from accelerate's harness (SURVEY §4.4), used by tests and bench; no
    video files, but the full transform stack still runs."""

    def __init__(
        self,
        transform: Callable,
        num_videos: int = 64,
        num_classes: int = 4,
        raw_frames: int = 24,
        raw_size: tuple = (72, 96),
        seed: int = 42,
        num_clips: int = 1,
    ):
        self.transform = transform
        self.num_videos = num_videos
        self.num_classes = num_classes
        self.raw_frames = raw_frames
        self.raw_size = raw_size
        self.seed = seed
        self.num_clips = max(num_clips, 1)

    def __len__(self) -> int:
        return self.num_videos

    def get(self, index: int, epoch: int) -> Dict[str, np.ndarray]:
        label = index % self.num_classes
        rng = np.random.default_rng((self.seed, epoch, index))
        h, w = self.raw_size

        def synth_span(a, b):  # label-coded random frames, span-independent
            frames = (rng.random((self.raw_frames, h, w, 3)) * 60).astype(np.uint8)
            frames += np.uint8(label * (160 // max(self.num_classes - 1, 1)))
            return frames

        out = sample_views(synth_span, self.transform, 1.0, 1.0,
                           training=self.num_clips == 1, rng=rng,
                           num_clips=self.num_clips)
        out["label"] = np.int32(label)
        return out


def stack_samples(arrs: List[np.ndarray]) -> np.ndarray:
    """np.stack via the native multithreaded gather-copy when available
    (GIL-free batch assembly); numpy fallback otherwise. Module-level so
    remote decode workers (dataplane/worker.py) assemble batches with the
    EXACT code path the local loader uses — byte parity by construction."""
    first = np.asarray(arrs[0])
    if first.ndim == 0:
        return np.stack(arrs)
    from pytorchvideo_accelerate_tpu.native.ringbuf import gather_copy

    out = np.empty((len(arrs), *first.shape), first.dtype)
    gather_copy(out, arrs)
    return out


def assemble_batch(samples: List[Dict[str, np.ndarray]], pad_to: int,
                   accum_steps: int = 1,
                   local_batch_size: Optional[int] = None) -> dict:
    """Stack per-sample dicts into one batch dict: padded + masked tail
    (val only) below `pad_to`, reshaped to (accum, B_local, ...) when
    `accum_steps > 1`. The single batch-assembly authority for the local
    loader AND the remote decode workers."""
    n = len(samples)
    keys = samples[0].keys()
    batch = {k: stack_samples([s[k] for s in samples]) for k in keys}
    if n < pad_to:  # padded tail (val only): mask marks real samples
        mask = np.zeros(pad_to, np.float32)
        mask[:n] = 1.0
        for k in list(batch):
            pad_shape = (pad_to - n, *batch[k].shape[1:])
            batch[k] = np.concatenate(
                [batch[k], np.zeros(pad_shape, batch[k].dtype)]
            )
        batch["mask"] = mask
    if accum_steps > 1:
        lb = local_batch_size if local_batch_size else pad_to // accum_steps
        batch = {
            k: v.reshape(accum_steps, lb, *v.shape[1:])
            for k, v in batch.items()
        }
    return batch


@dataclass
class LoaderState:
    """Checkpointable iterator position."""

    epoch: int = 0
    position: int = 0  # batches already yielded this epoch

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "position": self.position}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "LoaderState":
        d = d or {}
        return cls(epoch=int(d.get("epoch", 0)), position=int(d.get("position", 0)))


class ClipLoader:
    """Batches a ClipSource for one host of a data-parallel mesh.

    Yields numpy batch dicts shaped (B_local, ...) — or (accum, B_local, ...)
    when `accum_steps > 1` — ready for `parallel.sharding.shard_batch`.
    `global_batch_size` is the whole-mesh batch; B_local is this host's share.
    """

    def __init__(
        self,
        source: ClipSource,
        global_batch_size: int,
        accum_steps: int = 1,
        shuffle: bool = False,
        drop_last: bool = True,
        seed: int = 42,
        num_workers: int = 8,
        process_index: int = 0,
        process_count: int = 1,
        prefetch_batches: int = 2,
        transport: str = "thread",
    ):
        if global_batch_size % process_count:
            raise ValueError(
                f"global_batch_size {global_batch_size} not divisible by "
                f"process_count {process_count}"
            )
        if transport not in ("auto", "thread", "process"):
            raise ValueError(
                f"transport must be auto|thread|process, got {transport!r}")
        self.source = source
        self.global_batch_size = global_batch_size
        self.local_batch_size = global_batch_size // process_count
        self.accum_steps = max(accum_steps, 1)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.num_workers = max(num_workers, 1)
        self.process_index = process_index
        self.process_count = process_count
        self.prefetch_batches = prefetch_batches
        self.state = LoaderState()
        self._pool = ThreadPoolExecutor(max_workers=self.num_workers)
        # "process": forked decode workers + native shm ring (SURVEY N8);
        # falls back to threads when the native lib can't build.
        # "auto" = threads. Every measurement to date says so: cv2 decode
        # and numpy transforms release the GIL, threads beat the forked
        # shm-ring transport 7x on the production decode path and broke
        # even (0.996x) even on a deliberately GIL-bound pure-Python
        # augment stack (bench.py transport_crossover). An earlier >=16-core
        # heuristic here was extrapolation from a 1-core host — a guess,
        # not a measurement — so it is gone: the process transport is an
        # EXPLICIT opt-in for workloads whose transforms hold the GIL
        # (heavy pure-Python per-clip work), where the fork + shm-ring
        # overhead can pay for itself.
        self.transport = "thread" if transport == "auto" else transport
        self._shm_pool = None
        if self.transport == "process":
            import pytorchvideo_accelerate_tpu.native as native

            if native.load() is None:
                self.transport = "thread"

    # --- epoch geometry ---------------------------------------------------

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        idx = np.arange(len(self.source))
        if self.shuffle:
            rng = np.random.default_rng((self.seed, 0xDA7A, epoch))
            rng.shuffle(idx)
        idx = idx[self.process_index :: self.process_count]
        # bad-sample quarantine (data/manifest.Quarantine): sources that
        # track quarantined clips get them remapped onto clean ones HERE,
        # so a sidelined clip never reaches the decode pool and epoch
        # geometry (batch count, loader positions) stays unchanged
        quarantined = getattr(self.source, "quarantined_indices", None)
        if quarantined is not None:
            bad = quarantined()
            if bad:
                idx = substitute_indices(idx, bad, len(self.source),
                                         self.seed, epoch)
        return idx

    @property
    def samples_per_yield(self) -> int:
        return self.local_batch_size * self.accum_steps

    def batches_per_epoch(self) -> int:
        n = len(self.source) // self.process_count
        if self.drop_last:
            return n // self.samples_per_yield
        return -(-n // self.samples_per_yield)

    def steps_per_epoch(self) -> int:
        """Optimizer steps per epoch (one per yielded super-batch)."""
        return self.batches_per_epoch()

    # --- iteration --------------------------------------------------------

    @staticmethod
    def _stack(arrs: List[np.ndarray]) -> np.ndarray:
        return stack_samples(arrs)

    def _assemble(self, samples: List[Dict[str, np.ndarray]], pad_to: int) -> dict:
        return assemble_batch(samples, pad_to, accum_steps=self.accum_steps,
                              local_batch_size=self.local_batch_size)

    def epoch(self, epoch: Optional[int] = None,
              from_start: bool = False) -> Iterator[dict]:
        """Iterate one epoch, honoring and updating `self.state` (resume
        mid-epoch by restoring state before calling).

        `from_start=True` ignores any stored mid-epoch position — the eval
        contract: a previous early-broken pass (limit_val_batches) must not
        make the next pass silently skip its head batches."""
        for batch, state in self.epoch_items(epoch, from_start):
            self.state = state
            if batch is not None:
                yield batch

    def epoch_items(self, epoch: Optional[int] = None,
                    from_start: bool = False) -> Iterator[tuple]:
        """Like `epoch()`, but yields `(batch, LoaderState)` pairs and never
        mutates `self.state` — the post-consumption state rides alongside each
        batch, and a final `(None, rollover_state)` pair marks exhaustion.

        This is the contract the device prefetcher needs: it advances this
        generator from a background thread, so state assignment must happen
        on the CONSUMER side, when the trainer actually takes a batch —
        otherwise a mid-epoch checkpoint would record a position several
        prefetched batches ahead of what training consumed, and resume would
        silently skip them."""
        start_state = self._start_state(epoch, from_start)
        epoch = start_state.epoch
        indices = self._epoch_indices(epoch)
        spy = self.samples_per_yield
        n_batches = self.batches_per_epoch()
        if self.transport == "process":
            yield from self._epoch_process_items(
                epoch, start_state.position, indices, n_batches)
            return

        def fetch_one(i) -> Dict[str, np.ndarray]:
            # obs "decode" span: per-sample decode+transform wall time on
            # the worker threads (background-classed — it overlaps the
            # consumer loop, so it informs, never sums into, window wall)
            with obs.span("decode"):
                return self.source.get(int(i), epoch)

        def fetch_batch(b: int) -> dict:
            chunk = indices[b * spy : (b + 1) * spy]
            samples = list(self._pool.map(fetch_one, chunk))
            return self._assemble(samples, spy)

        start = start_state.position
        pending: "Queue[tuple]" = Queue()
        depth = max(self.prefetch_batches, 1)
        next_submit = start
        submitted = 0
        executor = ThreadPoolExecutor(max_workers=1)  # batch-assembly lane
        try:
            while next_submit < n_batches and submitted < depth:
                pending.put((next_submit, executor.submit(fetch_batch, next_submit)))
                next_submit += 1
                submitted += 1
            while not pending.empty():
                b, fut = pending.get()
                batch = fut.result()
                if next_submit < n_batches:
                    pending.put(
                        (next_submit, executor.submit(fetch_batch, next_submit))
                    )
                    next_submit += 1
                yield batch, LoaderState(epoch=epoch, position=b + 1)
            yield None, LoaderState(epoch=epoch + 1, position=0)
        finally:
            # early exit (limit_train_batches break -> GeneratorExit, or an
            # exception upstream): in-flight fetch_batch futures would keep
            # decoding whole batches after the consumer is gone. Cancel
            # everything still queued; shutdown(cancel_futures) catches any
            # race between the drain and a worker picking one up.
            while not pending.empty():
                try:
                    pending.get_nowait()[1].cancel()
                except Empty:  # pragma: no cover - single-consumer queue
                    break
            try:
                executor.shutdown(wait=False, cancel_futures=True)
            except TypeError:  # pragma: no cover - py<3.9 fallback
                executor.shutdown(wait=False)

    def _start_state(self, epoch: Optional[int],
                     from_start: bool) -> LoaderState:
        """Effective starting position for an epoch pass (pure; `epoch()` /
        the prefetcher assign it back to `self.state` batch by batch)."""
        if from_start:
            return LoaderState(
                epoch=self.state.epoch if epoch is None else epoch, position=0)
        if epoch is not None and epoch != self.state.epoch:
            return LoaderState(epoch=epoch, position=0)
        return self.state

    def _epoch_process_items(self, epoch: int, start: int,
                             indices: np.ndarray,
                             n_batches: int) -> Iterator[tuple]:
        """Forked shm workers; batches byte-identical to the thread path.
        Prefetch comes from ring capacity (workers run ahead of assembly)."""
        from pytorchvideo_accelerate_tpu.native.shm_loader import ShmWorkerPool

        spy = self.samples_per_yield
        if self._shm_pool is None:
            # assembly defers slot release until a full batch is collected;
            # worker w contributes ceil(spy/W) samples per batch, so each
            # per-worker ring must hold that many in-flight slots plus
            # prefetch headroom
            per_worker = -(-spy // self.num_workers) + 2
            self._shm_pool = ShmWorkerPool(
                self.source, num_workers=self.num_workers,
                slots_per_worker=per_worker,
            )
        usable = indices[: n_batches * spy] if self.drop_last else indices
        samples, dones = [], []
        b = start

        def flush():
            nonlocal samples, dones
            batch = self._assemble(samples, spy)
            for done in dones:
                done()
            samples, dones = [], []
            return batch

        for sample, done in self._shm_pool.map_epoch(
            usable, epoch, start=start * spy
        ):
            samples.append(sample)
            dones.append(done)
            if len(samples) == spy:
                yield flush(), LoaderState(epoch=epoch, position=b + 1)
                b += 1
        if samples:  # non-drop_last tail, padded + masked
            yield flush(), LoaderState(epoch=epoch, position=b + 1)
        yield None, LoaderState(epoch=epoch + 1, position=0)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        if self._shm_pool is not None:
            self._shm_pool.close()
            self._shm_pool = None
