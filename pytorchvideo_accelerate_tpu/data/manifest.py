"""Dataset manifest: dir-per-class video index, or a path+label list file.

Replaces pytorchvideo's `Kinetics` path/label discovery and the reference's
private-attribute label-count hack
(`train_dataset.dataset._labeled_videos._paths_and_labels`, run.py:185) with
an explicit, inspectable manifest over the same on-disk layout the reference
README documents (README.md:17: `data_dir/{train,val}/{class}/*.mp4`).

`from_list` additionally reads the path+label list format pytorchvideo's
`LabeledVideoDataset.from_csv` consumes (one `relative/path.mp4 <label>`
per line, space- or comma-separated) — how Kinetics/SSv2 splits are
commonly distributed — so users migrating with existing .csv/.txt split
files don't have to restructure their storage into class directories.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Tuple

VIDEO_EXTENSIONS = (".mp4", ".avi", ".mkv", ".webm", ".mov", ".m4v")


@dataclass(frozen=True)
class VideoEntry:
    path: str
    label: int
    label_name: str


@dataclass
class Manifest:
    entries: List[VideoEntry]
    class_names: List[str]  # sorted; index = label id

    @property
    def num_classes(self) -> int:
        return len(self.class_names)

    @property
    def num_videos(self) -> int:
        return len(self.entries)

    def __len__(self) -> int:
        return len(self.entries)


def from_list(list_path: str, root: str = "") -> Manifest:
    """Read a `path label` list file (pytorchvideo from_csv format: one
    video per line, space- or comma-separated, label an integer id).
    Relative paths resolve against `root`. Class ids come from the file;
    names are synthesized (`class_<id>`) since list files carry none —
    `Manifest.class_names` stays index-aligned either way."""
    if not os.path.isfile(list_path):
        raise FileNotFoundError(f"manifest list file not found: {list_path}")
    entries: List[VideoEntry] = []
    max_label = -1
    with open(list_path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            # comma (csv) or whitespace separated; label is the LAST field
            # so paths containing spaces survive the common space format
            parts = (line.rsplit(",", 1) if "," in line
                     else line.rsplit(None, 1))
            if len(parts) != 2:
                raise ValueError(
                    f"{list_path}:{lineno}: expected 'path label', "
                    f"got {line!r}")
            path, label_s = parts[0].strip(), parts[1].strip()
            try:
                label = int(label_s)
            except ValueError:
                raise ValueError(
                    f"{list_path}:{lineno}: label must be an integer id, "
                    f"got {label_s!r} (dir-per-class trees carry names; "
                    "list files carry ids)") from None
            if label < 0:
                raise ValueError(
                    f"{list_path}:{lineno}: negative label {label}")
            if root and not os.path.isabs(path):
                path = os.path.join(root, path)
            max_label = max(max_label, label)
            entries.append(VideoEntry(path, label, f"class_{label}"))
    if not entries:
        raise ValueError(f"no entries in {list_path}")
    class_names = [f"class_{i}" for i in range(max_label + 1)]
    return Manifest(entries=entries, class_names=class_names)


def scan_directory(split_dir: str) -> Manifest:
    """Scan `split_dir/{class}/*` into a manifest. Class ids are assigned by
    sorted class-dir name — deterministic across hosts (pytorchvideo sorts
    the same way [external])."""
    if not os.path.isdir(split_dir):
        raise FileNotFoundError(f"dataset split directory not found: {split_dir}")
    class_names = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d)) and not d.startswith(".")
    )
    if not class_names:
        raise ValueError(f"no class directories under {split_dir}")
    entries: List[VideoEntry] = []
    for label, name in enumerate(class_names):
        cdir = os.path.join(split_dir, name)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(VIDEO_EXTENSIONS):
                entries.append(VideoEntry(os.path.join(cdir, fname), label, name))
    if not entries:
        raise ValueError(f"no video files under {split_dir}")
    return Manifest(entries=entries, class_names=class_names)
