"""Dataset manifest: dir-per-class video index, or a path+label list file.

Replaces pytorchvideo's `Kinetics` path/label discovery and the reference's
private-attribute label-count hack
(`train_dataset.dataset._labeled_videos._paths_and_labels`, run.py:185) with
an explicit, inspectable manifest over the same on-disk layout the reference
README documents (README.md:17: `data_dir/{train,val}/{class}/*.mp4`).

`from_list` additionally reads the path+label list format pytorchvideo's
`LabeledVideoDataset.from_csv` consumes (one `relative/path.mp4 <label>`
per line, space- or comma-separated) — how Kinetics/SSv2 splits are
commonly distributed — so users migrating with existing .csv/.txt split
files don't have to restructure their storage into class directories.

`Quarantine` is the bad-sample sideline: real Kinetics-scale trees always
carry a few deterministically-corrupt files, and before PR 9 those cost a
retry + substitution *every epoch, at the same clip, forever* — or worse,
raised through after `_MAX_CONSECUTIVE_FAILURES` and killed a multi-day
run. Now each clip has a failure budget; exhausting it moves the path into
a persisted JSON sidecar that the sampler excludes (deterministic
substitute indices — epoch geometry unchanged), the epoch continues, the
`pva_data_quarantined_total{site=}` counter ticks, and `pva-tpu-doctor`
lists the quarantined set.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from pytorchvideo_accelerate_tpu.utils.sync import make_lock

VIDEO_EXTENSIONS = (".mp4", ".avi", ".mkv", ".webm", ".mov", ".m4v")


@dataclass(frozen=True)
class VideoEntry:
    path: str
    label: int
    label_name: str


@dataclass
class Manifest:
    entries: List[VideoEntry]
    class_names: List[str]  # sorted; index = label id

    @property
    def num_classes(self) -> int:
        return len(self.class_names)

    @property
    def num_videos(self) -> int:
        return len(self.entries)

    def __len__(self) -> int:
        return len(self.entries)


class Quarantine:
    """Persisted per-clip failure budget + the quarantined-path sidecar.

    `record(path, error)` counts one decode-layer failure against `path`;
    the `budget`-th failure quarantines it: the path lands in the sidecar
    JSON (atomic write — a kill mid-update can't corrupt the list), the
    `pva_data_quarantined_total{site=}` counter ticks, and every sampler/
    source consulting `contains()` / the exclusion helpers skips the clip
    from then on (including the NEXT run: the sidecar is read back at
    construction). Thread-safe — decode-pool workers record concurrently.

    The budget exists so one transient NFS blip never sidelines a healthy
    clip: only repeated failures (a deterministically corrupt file fails
    every epoch) cross it. `budget=1` quarantines on first failure.
    """

    def __init__(self, sidecar_path: str, budget: int = 3,
                 site: str = "decode"):
        self.sidecar_path = sidecar_path
        self.budget = max(int(budget), 1)
        self.site = site
        self._lock = make_lock("Quarantine._lock")
        self._failures: Dict[str, int] = {}
        self._quarantined: Dict[str, str] = {}  # path -> last error head
        if sidecar_path and os.path.exists(sidecar_path):
            try:
                with open(sidecar_path) as f:
                    data = json.load(f)
                self._quarantined = dict(data.get("quarantined", {}))
                self._failures = {k: int(v) for k, v in
                                  data.get("failures", {}).items()}
            except (OSError, ValueError):
                # an unreadable sidecar starts fresh — quarantine is an
                # optimization, never a reason to refuse to train
                self._quarantined, self._failures = {}, {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._quarantined)

    def contains(self, path: str) -> bool:
        with self._lock:
            return path in self._quarantined

    def paths(self) -> set:
        with self._lock:
            return set(self._quarantined)

    def snapshot(self) -> dict:
        """Doctor/report view: quarantined paths with evidence + pending
        failure counts still under budget."""
        with self._lock:
            return {"budget": self.budget,
                    "quarantined": dict(self._quarantined),
                    "failures_under_budget": {
                        p: c for p, c in self._failures.items()
                        if p not in self._quarantined}}

    def record(self, path: str, error: Optional[BaseException] = None) -> bool:
        """Count one failure; returns True when this call NEWLY quarantined
        the path (callers log/count exactly once)."""
        head = f"{type(error).__name__}: {error}"[:200] if error else ""
        with self._lock:
            if path in self._quarantined:
                return False
            n = self._failures.get(path, 0) + 1
            self._failures[path] = n
            if n < self.budget:
                newly = False
            else:
                self._quarantined[path] = head
                newly = True
            payload = {"budget": self.budget,
                       "failures": dict(self._failures),
                       "quarantined": dict(self._quarantined)}
            # persisted UNDER the lock: two concurrent records could
            # otherwise land their atomic writes out of snapshot order and
            # the stale writer would win, losing a failure count (cold
            # path — a decode failure already cost retries + a warning)
            self._persist(payload)
        if newly:
            self._publish(path, head)
        return newly

    def _persist(self, payload: dict) -> None:
        if not self.sidecar_path:
            return
        try:
            from pytorchvideo_accelerate_tpu.reliability.atomic import (
                atomic_write_json,
            )

            atomic_write_json(self.sidecar_path, payload)  # pva: disable=spmd-divergence -- per-host data-shard state, not a shared artifact: each host quarantines its own shard; pod runs get per-process sidecar paths with the multi-host PR
        except OSError:  # pragma: no cover - sideline must not kill decode
            pass

    def _publish(self, path: str, head: str) -> None:
        try:
            from pytorchvideo_accelerate_tpu.obs import (
                get_recorder,
                get_registry,
            )

            get_registry().counter(
                "pva_data_quarantined_total",
                "clips quarantined after exhausting the failure budget, "
                "by site", labelnames=("site",)).inc(site=self.site)
            get_recorder().warn("clip quarantined", path=path, error=head)
        except Exception:  # pragma: no cover - telemetry stays optional
            pass


def from_list(list_path: str, root: str = "") -> Manifest:
    """Read a `path label` list file (pytorchvideo from_csv format: one
    video per line, space- or comma-separated, label an integer id).
    Relative paths resolve against `root`. Class ids come from the file;
    names are synthesized (`class_<id>`) since list files carry none —
    `Manifest.class_names` stays index-aligned either way."""
    if not os.path.isfile(list_path):
        raise FileNotFoundError(f"manifest list file not found: {list_path}")
    entries: List[VideoEntry] = []
    max_label = -1
    with open(list_path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            # comma (csv) or whitespace separated; label is the LAST field
            # so paths containing spaces survive the common space format
            parts = (line.rsplit(",", 1) if "," in line
                     else line.rsplit(None, 1))
            if len(parts) != 2:
                raise ValueError(
                    f"{list_path}:{lineno}: expected 'path label', "
                    f"got {line!r}")
            path, label_s = parts[0].strip(), parts[1].strip()
            try:
                label = int(label_s)
            except ValueError:
                raise ValueError(
                    f"{list_path}:{lineno}: label must be an integer id, "
                    f"got {label_s!r} (dir-per-class trees carry names; "
                    "list files carry ids)") from None
            if label < 0:
                raise ValueError(
                    f"{list_path}:{lineno}: negative label {label}")
            if root and not os.path.isabs(path):
                path = os.path.join(root, path)
            max_label = max(max_label, label)
            entries.append(VideoEntry(path, label, f"class_{label}"))
    if not entries:
        raise ValueError(f"no entries in {list_path}")
    class_names = [f"class_{i}" for i in range(max_label + 1)]
    return Manifest(entries=entries, class_names=class_names)


def scan_directory(split_dir: str) -> Manifest:
    """Scan `split_dir/{class}/*` into a manifest. Class ids are assigned by
    sorted class-dir name — deterministic across hosts (pytorchvideo sorts
    the same way [external])."""
    if not os.path.isdir(split_dir):
        raise FileNotFoundError(f"dataset split directory not found: {split_dir}")
    class_names = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d)) and not d.startswith(".")
    )
    if not class_names:
        raise ValueError(f"no class directories under {split_dir}")
    entries: List[VideoEntry] = []
    for label, name in enumerate(class_names):
        cdir = os.path.join(split_dir, name)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(VIDEO_EXTENSIONS):
                entries.append(VideoEntry(os.path.join(cdir, fname), label, name))
    if not entries:
        raise ValueError(f"no video files under {split_dir}")
    return Manifest(entries=entries, class_names=class_names)
