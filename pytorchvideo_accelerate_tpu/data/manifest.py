"""Dataset manifest: dir-per-class video index.

Replaces pytorchvideo's `Kinetics` path/label discovery and the reference's
private-attribute label-count hack
(`train_dataset.dataset._labeled_videos._paths_and_labels`, run.py:185) with
an explicit, inspectable manifest over the same on-disk layout the reference
README documents (README.md:17: `data_dir/{train,val}/{class}/*.mp4`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Tuple

VIDEO_EXTENSIONS = (".mp4", ".avi", ".mkv", ".webm", ".mov", ".m4v")


@dataclass(frozen=True)
class VideoEntry:
    path: str
    label: int
    label_name: str


@dataclass
class Manifest:
    entries: List[VideoEntry]
    class_names: List[str]  # sorted; index = label id

    @property
    def num_classes(self) -> int:
        return len(self.class_names)

    @property
    def num_videos(self) -> int:
        return len(self.entries)

    def __len__(self) -> int:
        return len(self.entries)


def scan_directory(split_dir: str) -> Manifest:
    """Scan `split_dir/{class}/*` into a manifest. Class ids are assigned by
    sorted class-dir name — deterministic across hosts (pytorchvideo sorts
    the same way [external])."""
    if not os.path.isdir(split_dir):
        raise FileNotFoundError(f"dataset split directory not found: {split_dir}")
    class_names = sorted(
        d for d in os.listdir(split_dir)
        if os.path.isdir(os.path.join(split_dir, d)) and not d.startswith(".")
    )
    if not class_names:
        raise ValueError(f"no class directories under {split_dir}")
    entries: List[VideoEntry] = []
    for label, name in enumerate(class_names):
        cdir = os.path.join(split_dir, name)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(VIDEO_EXTENSIONS):
                entries.append(VideoEntry(os.path.join(cdir, fname), label, name))
    if not entries:
        raise ValueError(f"no video files under {split_dir}")
    return Manifest(entries=entries, class_names=class_names)
