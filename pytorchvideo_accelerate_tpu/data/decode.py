"""Video decode via OpenCV (bundled FFmpeg).

Replaces the reference stack's PyAV->libav decode path (SURVEY §2.3-N9:
pytorchvideo `EncodedVideo` with `decode_audio=False`, run.py:155,164). The
build image has no system ffmpeg binary and no PyAV; cv2's VideoCapture is
the C++ decode engine available to every worker thread (it releases the GIL,
so a thread pool gives real decode parallelism — see pipeline.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from pytorchvideo_accelerate_tpu.reliability.faults import fault_point

try:
    import cv2
except Exception:  # pragma: no cover
    cv2 = None

# What "this video is unreadable" looks like from the decode layer — the
# single source of truth for every caller that degrades gracefully
# (pipeline substitution, cache-build skip, the verify doctor). cv2.error
# subclasses Exception only, so it is listed explicitly when available.
DECODE_ERRORS = ((IOError, OSError, ValueError, RuntimeError, cv2.error)
                 if cv2 is not None and hasattr(cv2, "error")
                 else (IOError, OSError, ValueError, RuntimeError))


class CorruptVideoError(IOError):
    """The decoder's own verdict that the FILE is bad (container won't
    open, zero frames in a valid span) — as opposed to an ambient OSError
    from flaky storage. Still an IOError, so it rides DECODE_ERRORS into
    the same retry/substitution machinery; the bad-sample quarantine
    (`data/manifest.py Quarantine`) counts these against the per-clip
    failure budget that eventually sidelines a deterministically-corrupt
    clip instead of letting it kill every epoch at the same index."""


@dataclass
class VideoMeta:
    fps: float
    frame_count: int

    @property
    def duration(self) -> float:
        return self.frame_count / self.fps if self.fps > 0 else 0.0


def probe(path: str) -> VideoMeta:
    cap = cv2.VideoCapture(path)
    try:
        if not cap.isOpened():
            raise CorruptVideoError(f"cannot open video: {path}")
        fps = cap.get(cv2.CAP_PROP_FPS) or 30.0
        frame_count = int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
        return VideoMeta(fps=float(fps), frame_count=frame_count)
    finally:
        cap.release()


def decode_span(path: str, start_sec: float, end_sec: float,
                max_frames: Optional[int] = None) -> np.ndarray:
    """Decode frames in [start_sec, end_sec) as (T, H, W, 3) RGB uint8.

    Seeks to the start frame, then reads sequentially — the access pattern
    clip sampling produces. Raises IOError on unreadable files; returns at
    least one frame for any readable video (short videos yield what exists,
    mirroring pytorchvideo's clamp-to-duration behavior [external]).
    """
    # chaos hook (reliability/faults.py): disarmed = one global read. An
    # injected fault IS an OSError, so it rides DECODE_ERRORS into the
    # same retry/substitution machinery a real unreadable file exercises.
    fault_point("decode.read", path=path)
    cap = cv2.VideoCapture(path)
    try:
        if not cap.isOpened():
            raise CorruptVideoError(f"cannot open video: {path}")
        fps = cap.get(cv2.CAP_PROP_FPS) or 30.0
        start_frame = max(int(round(start_sec * fps)), 0)
        end_frame = max(int(round(end_sec * fps)), start_frame + 1)
        if max_frames is not None:
            end_frame = min(end_frame, start_frame + max_frames)
        if start_frame > 0:
            cap.set(cv2.CAP_PROP_POS_FRAMES, start_frame)
        frames = []
        for _ in range(end_frame - start_frame):
            ok, frame_bgr = cap.read()
            if not ok:
                break
            frames.append(cv2.cvtColor(frame_bgr, cv2.COLOR_BGR2RGB))
        if not frames:
            raise CorruptVideoError(
                f"no frames decoded from {path} in [{start_sec:.2f}, {end_sec:.2f})s"
            )
        return np.stack(frames)
    finally:
        cap.release()
