"""Pre-decoded frame cache: the array_record-style fallback of SURVEY §7
hard-part 1 ("host decode is the likely real bottleneck").

The reference pays a full PyAV decode per sampled clip every epoch
(run.py:155,164 via pytorchvideo `EncodedVideo` [external]). This module
trades disk for decode CPU: an offline pass decodes every manifest video
ONCE into a flat uint8 frame store + JSON index; training then serves any
clip span as a memmap slice — O(1), no codec in the hot path, and the
random-access pattern clip sampling produces is exactly what a memmap is
good at.

Format (directory):
    index.json   {"fps": F, "short_side": S, "videos": [{"path", "label",
                  "offset", "frames", "height", "width"}, ...]}
    data.bin     concatenated (T_i, H_i, W_i, 3) uint8 frame blocks

Videos keep their aspect ratio (short side scaled to `short_side`), so
records vary in H/W; offsets are byte positions into data.bin. One file +
one index keeps the filesystem metadata load trivial (vs a file per clip)
and the read path a single pread per clip.

CLI:
    python -m pytorchvideo_accelerate_tpu.data.cache build \
        --data_dir /data/kinetics/train --out /ssd/kinetics_train_cache \
        [--fps 30] [--short_side 320] [--num_workers 8]
"""

from __future__ import annotations

import json
import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

import numpy as np

from pytorchvideo_accelerate_tpu.data import decode as decode_mod
from pytorchvideo_accelerate_tpu.data.manifest import Manifest, scan_directory
from pytorchvideo_accelerate_tpu.data.samplers import random_clip

logger = logging.getLogger(__name__)

INDEX_NAME = "index.json"
DATA_NAME = "data.bin"


def _scaled_size(h: int, w: int, short_side: int) -> tuple:
    if min(h, w) <= short_side:
        return h, w
    if h < w:
        return short_side, max(int(round(w * short_side / h)), 1)
    return max(int(round(h * short_side / w)), 1), short_side


def _decode_video(path: str, fps: float, short_side: int) -> np.ndarray:
    """Decode a whole video resampled to `fps`, short side <= `short_side`."""
    import cv2

    meta = decode_mod.probe(path)
    frames = decode_mod.decode_span(path, 0.0, meta.duration)
    # temporal resample to the cache fps (nearest frame)
    if abs(meta.fps - fps) > 1e-3 and meta.fps > 0:
        n_out = max(int(round(len(frames) * fps / meta.fps)), 1)
        idx = np.clip(
            np.round(np.arange(n_out) * meta.fps / fps).astype(np.int64),
            0, len(frames) - 1,
        )
        frames = frames[idx]
    h, w = frames.shape[1:3]
    sh, sw = _scaled_size(h, w, short_side)
    if (sh, sw) != (h, w):
        frames = np.stack(
            [cv2.resize(f, (sw, sh), interpolation=cv2.INTER_LINEAR)
             for f in frames]
        )
    return np.ascontiguousarray(frames)


def build_cache(data_dir: str, out_dir: str, fps: float = 30.0,
                short_side: int = 320, num_workers: int = 8,
                manifest: Optional[Manifest] = None) -> dict:
    """Offline transcode: manifest videos -> frame store. Returns the index.

    Decode runs in a thread pool (cv2 releases the GIL); writes are
    sequential appends in manifest order, so the output is deterministic.
    """
    manifest = manifest or scan_directory(data_dir)
    os.makedirs(out_dir, exist_ok=True)
    videos: List[dict] = []
    pool = ThreadPoolExecutor(max_workers=max(num_workers, 1))
    try:
        # bounded decode-ahead window: the writer consumes in manifest order,
        # so unbounded submission would buffer whole decoded videos
        # (~100s of MB each) while it catches up
        from collections import deque

        window = max(num_workers, 1) * 2
        pending = deque()
        for e in manifest.entries[:window]:
            pending.append((e, pool.submit(_decode_video, e.path, fps,
                                           short_side)))
        consumed = len(pending)
        offset = 0
        with open(os.path.join(out_dir, DATA_NAME), "wb") as f:
            while pending:
                entry, fut = pending.popleft()
                try:
                    frames = fut.result()
                except decode_mod.DECODE_ERRORS as e:
                    # corrupt source video: skip (real Kinetics trees always
                    # have some) — it simply doesn't appear in the index
                    logger.warning("cache build: skipping unreadable %s "
                                   "(%s: %s)", entry.path, type(e).__name__, e)
                    frames = None
                if consumed < len(manifest.entries):
                    nxt = manifest.entries[consumed]
                    pending.append((nxt, pool.submit(_decode_video, nxt.path,
                                                     fps, short_side)))
                    consumed += 1
                if frames is None:
                    continue
                f.write(frames.tobytes())
                videos.append({
                    "path": entry.path,
                    "label": int(entry.label),
                    "offset": offset,
                    "frames": int(frames.shape[0]),
                    "height": int(frames.shape[1]),
                    "width": int(frames.shape[2]),
                })
                offset += frames.nbytes
    finally:
        pool.shutdown(wait=True, cancel_futures=True)
    index = {
        "fps": float(fps),
        "short_side": int(short_side),
        "num_classes": manifest.num_classes,
        "videos": videos,
    }
    with open(os.path.join(out_dir, INDEX_NAME), "w") as f:
        json.dump(index, f)
    return index


class FrameCache:
    """Memmap view over a built cache; `read(i, start_sec, end_sec)` returns
    (T, H, W, 3) uint8 — the `decode_span` contract, without the decode."""

    def __init__(self, cache_dir: str):
        with open(os.path.join(cache_dir, INDEX_NAME)) as f:
            self.index = json.load(f)
        self.fps = float(self.index["fps"])
        self.num_classes = int(self.index.get("num_classes", 0))
        self.videos = self.index["videos"]
        self._data = np.memmap(os.path.join(cache_dir, DATA_NAME),
                               dtype=np.uint8, mode="r")

    def __len__(self) -> int:
        return len(self.videos)

    def duration(self, i: int) -> float:
        return self.videos[i]["frames"] / self.fps

    def label(self, i: int) -> int:
        return self.videos[i]["label"]

    def read(self, i: int, start_sec: float, end_sec: float) -> np.ndarray:
        v = self.videos[i]
        t, h, w = v["frames"], v["height"], v["width"]
        start = min(max(int(round(start_sec * self.fps)), 0), t - 1)
        end = min(max(int(round(end_sec * self.fps)), start + 1), t)
        stride = h * w * 3
        lo = v["offset"] + start * stride
        hi = v["offset"] + end * stride
        return np.asarray(self._data[lo:hi]).reshape(end - start, h, w, 3)


class CachedClipSource:
    """Drop-in `ClipSource` over a FrameCache (same sampling semantics as
    VideoClipSource, including eval multi-view)."""

    def __init__(self, cache_dir: str, transform: Callable,
                 clip_duration: float, training: bool, seed: int = 42,
                 num_clips: int = 1):
        self.cache = FrameCache(cache_dir)
        self.transform = transform
        self.clip_duration = clip_duration
        self.training = training
        self.seed = seed
        self.num_clips = max(num_clips, 1) if not training else 1
        self.num_classes = self.cache.num_classes

    def __len__(self) -> int:
        return len(self.cache)

    def get(self, index: int, epoch: int) -> Dict[str, np.ndarray]:
        from pytorchvideo_accelerate_tpu.data.pipeline import sample_views

        rng = np.random.default_rng((self.seed, epoch, index))
        out = sample_views(
            lambda a, b: self.cache.read(index, a, b), self.transform,
            self.cache.duration(index), self.clip_duration, self.training,
            rng, self.num_clips,
        )
        out["label"] = np.int32(self.cache.label(index))
        return out


def measure_clip_throughput(fetch: Callable[[int], np.ndarray], n_items: int,
                            n_clips: int, num_workers: int = 1) -> float:
    """Clips/sec of `fetch(i)` over a thread pool (the loader's access
    pattern); used by the `bench` subcommand and tests."""
    import time

    pool = ThreadPoolExecutor(max_workers=max(num_workers, 1))
    try:
        list(pool.map(fetch, range(min(2, n_clips))))  # warm caches
        t0 = time.perf_counter()
        for arr in pool.map(fetch, (i % n_items for i in range(n_clips))):
            np.add.reduce(arr[0, 0, 0])  # touch the data (defeat lazy maps)
        return n_clips / (time.perf_counter() - t0)
    finally:
        pool.shutdown(wait=False)


def bench_decode_vs_cache(data_dir: str, cache_dir: str,
                          clip_duration: float = 2.0, n_clips: int = 64,
                          num_workers: int = 4, seed: int = 0) -> dict:
    """Measure raw-decode vs cache clips/sec on the same sampled spans
    (SURVEY §7 hard-part 1: quantify the decode bottleneck)."""
    manifest = scan_directory(data_dir)
    cache = FrameCache(cache_dir)
    rng = np.random.default_rng(seed)
    spans = []
    for i in range(len(manifest)):
        d = decode_mod.probe(manifest.entries[i].path).duration
        spans.append(random_clip(d, clip_duration, rng))

    def fetch_decode(i):
        s = spans[i]
        return decode_mod.decode_span(manifest.entries[i].path, s.start, s.end)

    def fetch_cache(i):
        s = spans[i]
        return cache.read(i, s.start, s.end)

    decode_cps = measure_clip_throughput(fetch_decode, len(manifest),
                                         n_clips, num_workers)
    cache_cps = measure_clip_throughput(fetch_cache, len(manifest),
                                        n_clips, num_workers)
    return {
        "decode_clips_per_sec": round(decode_cps, 2),
        "cache_clips_per_sec": round(cache_cps, 2),
        "speedup": round(cache_cps / decode_cps, 2),
        "num_workers": num_workers,
    }


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("build", help="decode a manifest directory into a cache")
    b.add_argument("--data_dir", required=True)
    b.add_argument("--out", required=True)
    b.add_argument("--fps", type=float, default=30.0)
    b.add_argument("--short_side", type=int, default=320)
    b.add_argument("--num_workers", type=int, default=8)
    m = sub.add_parser("bench", help="decode vs cache clips/sec microbench")
    m.add_argument("--data_dir", required=True)
    m.add_argument("--cache_dir", required=True)
    m.add_argument("--clip_duration", type=float, default=2.0)
    m.add_argument("--clips", type=int, default=64)
    m.add_argument("--num_workers", type=int, default=4)
    args = ap.parse_args(argv)

    if args.cmd == "build":
        index = build_cache(args.data_dir, args.out, fps=args.fps,
                            short_side=args.short_side,
                            num_workers=args.num_workers)
        total = sum(v["frames"] for v in index["videos"])
        size = os.path.getsize(os.path.join(args.out, DATA_NAME))
        print(f"cached {len(index['videos'])} videos, {total} frames, "
              f"{size / 1e9:.2f} GB -> {args.out}")
    else:
        print(json.dumps(bench_decode_vs_cache(
            args.data_dir, args.cache_dir, clip_duration=args.clip_duration,
            n_clips=args.clips, num_workers=args.num_workers)))


if __name__ == "__main__":
    main()
