"""Pre-decoded frame cache: the array_record-style fallback of SURVEY §7
hard-part 1 ("host decode is the likely real bottleneck").

The reference pays a full PyAV decode per sampled clip every epoch
(run.py:155,164 via pytorchvideo `EncodedVideo` [external]). This module
trades disk for decode CPU: an offline pass decodes every manifest video
ONCE into a flat uint8 frame store + JSON index; training then serves any
clip span as a memmap slice — O(1), no codec in the hot path, and the
random-access pattern clip sampling produces is exactly what a memmap is
good at.

Format (directory):
    index.json   {"fps": F, "short_side": S, "videos": [{"path", "label",
                  "offset", "frames", "height", "width"}, ...]}
    data.bin     concatenated (T_i, H_i, W_i, 3) uint8 frame blocks

Videos keep their aspect ratio (short side scaled to `short_side`), so
records vary in H/W; offsets are byte positions into data.bin. One file +
one index keeps the filesystem metadata load trivial (vs a file per clip)
and the read path a single pread per clip.

CLI:
    python -m pytorchvideo_accelerate_tpu.data.cache build \
        --data_dir /data/kinetics/train --out /ssd/kinetics_train_cache \
        [--fps 30] [--short_side 320] [--num_workers 8]
"""

from __future__ import annotations

import json
import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

import numpy as np

from pytorchvideo_accelerate_tpu.data import decode as decode_mod
from pytorchvideo_accelerate_tpu.data.manifest import Manifest, scan_directory
from pytorchvideo_accelerate_tpu.data.samplers import random_clip

logger = logging.getLogger(__name__)

INDEX_NAME = "index.json"
DATA_NAME = "data.bin"


def _scaled_size(h: int, w: int, short_side: int) -> tuple:
    if min(h, w) <= short_side:
        return h, w
    if h < w:
        return short_side, max(int(round(w * short_side / h)), 1)
    return max(int(round(h * short_side / w)), 1), short_side


def _decode_video(path: str, fps: float, short_side: int) -> np.ndarray:
    """Decode a whole video resampled to `fps`, short side <= `short_side`."""
    import cv2

    meta = decode_mod.probe(path)
    frames = decode_mod.decode_span(path, 0.0, meta.duration)
    # temporal resample to the cache fps (nearest frame)
    if abs(meta.fps - fps) > 1e-3 and meta.fps > 0:
        n_out = max(int(round(len(frames) * fps / meta.fps)), 1)
        idx = np.clip(
            np.round(np.arange(n_out) * meta.fps / fps).astype(np.int64),
            0, len(frames) - 1,
        )
        frames = frames[idx]
    h, w = frames.shape[1:3]
    sh, sw = _scaled_size(h, w, short_side)
    if (sh, sw) != (h, w):
        frames = np.stack(
            [cv2.resize(f, (sw, sh), interpolation=cv2.INTER_LINEAR)
             for f in frames]
        )
    return np.ascontiguousarray(frames)


def build_cache(data_dir: str, out_dir: str, fps: float = 30.0,
                short_side: int = 320, num_workers: int = 8,
                manifest: Optional[Manifest] = None) -> dict:
    """Offline transcode: manifest videos -> frame store. Returns the index.

    Decode runs in a thread pool (cv2 releases the GIL); writes are
    sequential appends in manifest order, so the output is deterministic.
    """
    manifest = manifest or scan_directory(data_dir)
    os.makedirs(out_dir, exist_ok=True)
    videos: List[dict] = []
    pool = ThreadPoolExecutor(max_workers=max(num_workers, 1))
    try:
        # bounded decode-ahead window: the writer consumes in manifest order,
        # so unbounded submission would buffer whole decoded videos
        # (~100s of MB each) while it catches up
        from collections import deque

        window = max(num_workers, 1) * 2
        pending = deque()
        for e in manifest.entries[:window]:
            pending.append((e, pool.submit(_decode_video, e.path, fps,
                                           short_side)))
        consumed = len(pending)
        offset = 0
        with open(os.path.join(out_dir, DATA_NAME), "wb") as f:
            while pending:
                entry, fut = pending.popleft()
                try:
                    frames = fut.result()
                except decode_mod.DECODE_ERRORS as e:
                    # corrupt source video: skip (real Kinetics trees always
                    # have some) — it simply doesn't appear in the index
                    logger.warning("cache build: skipping unreadable %s "
                                   "(%s: %s)", entry.path, type(e).__name__, e)
                    frames = None
                if consumed < len(manifest.entries):
                    nxt = manifest.entries[consumed]
                    pending.append((nxt, pool.submit(_decode_video, nxt.path,
                                                     fps, short_side)))
                    consumed += 1
                if frames is None:
                    continue
                f.write(frames.tobytes())
                videos.append({
                    "path": entry.path,
                    "label": int(entry.label),
                    "offset": offset,
                    "frames": int(frames.shape[0]),
                    "height": int(frames.shape[1]),
                    "width": int(frames.shape[2]),
                })
                offset += frames.nbytes
    finally:
        pool.shutdown(wait=True, cancel_futures=True)
    index = {
        "fps": float(fps),
        "short_side": int(short_side),
        "num_classes": manifest.num_classes,
        "videos": videos,
    }
    with open(os.path.join(out_dir, INDEX_NAME), "w") as f:
        json.dump(index, f)
    return index


class FrameCache:
    """Memmap view over a built cache; `read(i, start_sec, end_sec)` returns
    (T, H, W, 3) uint8 — the `decode_span` contract, without the decode."""

    def __init__(self, cache_dir: str):
        with open(os.path.join(cache_dir, INDEX_NAME)) as f:
            self.index = json.load(f)
        self.fps = float(self.index["fps"])
        self.num_classes = int(self.index.get("num_classes", 0))
        self.videos = self.index["videos"]
        self._data = np.memmap(os.path.join(cache_dir, DATA_NAME),
                               dtype=np.uint8, mode="r")

    def __len__(self) -> int:
        return len(self.videos)

    def duration(self, i: int) -> float:
        return self.videos[i]["frames"] / self.fps

    def label(self, i: int) -> int:
        return self.videos[i]["label"]

    def byte_range(self, i: int, start_sec: float, end_sec: float):
        """(lo, hi, shape) of a clip span inside data.bin — the single
        home of the clamp/stride math (read() and the cold bench share
        it, so their semantics can't diverge)."""
        v = self.videos[i]
        t, h, w = v["frames"], v["height"], v["width"]
        start = min(max(int(round(start_sec * self.fps)), 0), t - 1)
        end = min(max(int(round(end_sec * self.fps)), start + 1), t)
        stride = h * w * 3
        lo = v["offset"] + start * stride
        hi = v["offset"] + end * stride
        return lo, hi, (end - start, h, w, 3)

    def read(self, i: int, start_sec: float, end_sec: float) -> np.ndarray:
        lo, hi, shape = self.byte_range(i, start_sec, end_sec)
        return np.asarray(self._data[lo:hi]).reshape(shape)

    def close(self) -> None:
        """Release the memmap (its live PTEs pin pages against page-cache
        eviction — the cold bench needs them gone)."""
        mm = getattr(self._data, "_mmap", None)
        self._data = None
        if mm is not None:
            mm.close()


class CachedClipSource:
    """Drop-in `ClipSource` over a FrameCache (same sampling semantics as
    VideoClipSource, including eval multi-view)."""

    def __init__(self, cache_dir: str, transform: Callable,
                 clip_duration: float, training: bool, seed: int = 42,
                 num_clips: int = 1):
        self.cache = FrameCache(cache_dir)
        self.transform = transform
        self.clip_duration = clip_duration
        self.training = training
        self.seed = seed
        self.num_clips = max(num_clips, 1) if not training else 1
        self.num_classes = self.cache.num_classes

    def __len__(self) -> int:
        return len(self.cache)

    def get(self, index: int, epoch: int) -> Dict[str, np.ndarray]:
        from pytorchvideo_accelerate_tpu.data.pipeline import sample_views

        rng = np.random.default_rng((self.seed, epoch, index))
        out = sample_views(
            lambda a, b: self.cache.read(index, a, b), self.transform,
            self.cache.duration(index), self.clip_duration, self.training,
            rng, self.num_clips,
        )
        out["label"] = np.int32(self.cache.label(index))
        return out


def measure_clip_throughput(fetch: Callable[[int], np.ndarray], n_items: int,
                            n_clips: int, num_workers: int = 1) -> float:
    """Clips/sec of `fetch(i)` over a thread pool (the loader's access
    pattern); used by the `bench` subcommand and tests."""
    import time

    pool = ThreadPoolExecutor(max_workers=max(num_workers, 1))
    try:
        list(pool.map(fetch, range(min(2, n_clips))))  # warm caches
        t0 = time.perf_counter()
        for arr in pool.map(fetch, (i % n_items for i in range(n_clips))):
            np.add.reduce(arr[0, 0, 0])  # touch the data (defeat lazy maps)
        return n_clips / (time.perf_counter() - t0)
    finally:
        pool.shutdown(wait=False)


def bench_decode_vs_cache(data_dir: str, cache_dir: str,
                          clip_duration: float = 2.0, n_clips: int = 64,
                          num_workers: int = 4, seed: int = 0) -> dict:
    """Measure raw-decode vs cache clips/sec on the same sampled spans
    (SURVEY §7 hard-part 1: quantify the decode bottleneck)."""
    manifest = scan_directory(data_dir)
    cache = FrameCache(cache_dir)
    rng = np.random.default_rng(seed)
    # build_cache skips corrupt videos, so cache indices need not equal
    # manifest positions ("real Kinetics trees always have some"): pair
    # each cached video with its manifest entry by path, and sample spans
    # only for the pairable ones
    cache_idx_by_path = {v["path"]: j for j, v in enumerate(cache.videos)}
    pairs = []  # (manifest_path, cache_idx, span)
    for e in manifest.entries:
        j = cache_idx_by_path.get(e.path)
        if j is None:
            continue
        d = decode_mod.probe(e.path).duration
        pairs.append((e.path, j, random_clip(d, clip_duration, rng)))
    if not pairs:
        return {"error": "cache shares no videos with the manifest"}

    def fetch_decode(i):
        path, _, s = pairs[i]
        return decode_mod.decode_span(path, s.start, s.end)

    def fetch_cache(i):
        _, j, s = pairs[i]
        return cache.read(j, s.start, s.end)

    decode_cps = measure_clip_throughput(fetch_decode, len(pairs),
                                         n_clips, num_workers)
    cache_cps = measure_clip_throughput(fetch_cache, len(pairs),
                                        n_clips, num_workers)
    out = {
        "decode_clips_per_sec": round(decode_cps, 2),
        "cache_clips_per_sec": round(cache_cps, 2),
        "speedup": round(cache_cps / decode_cps, 2),
        "num_workers": num_workers,
    }
    ranges = [cache.byte_range(j, s.start, s.end) for _, j, s in pairs]
    cache.close()  # live memmap PTEs would pin pages against eviction
    cold = _bench_cache_cold(os.path.join(cache_dir, DATA_NAME), ranges,
                             n_clips=min(n_clips, 32))
    if cold:
        out.update(cold)
    return out


def _bench_cache_cold(data_path: str, ranges, n_clips: int) -> Optional[dict]:
    """Storage-bound cache read rate: the warm number above is page-cache-
    resident (VERDICT r4 weak #3), so this path reads spans with plain
    pread after evicting exactly those bytes from the page cache
    (posix_fadvise DONTNEED, range-limited, issued OUTSIDE the timed
    region so O(eviction) kernel work isn't billed to the read). The
    caller must have closed any mmap over the file first — live PTEs make
    DONTNEED a no-op — and the file is fsync'd because DONTNEED won't
    drop dirty pages (a freshly built cache is still dirty). Bounds what
    cold storage can feed; the truth for a training run lies between this
    and the warm number, depending on how much of the cache fits in RAM.
    On a VM, a hypervisor-level cache below virtio can still serve the
    "cold" read — treat the result as an upper bound of storage speed."""
    import time

    if not hasattr(os, "posix_fadvise"):
        return None
    try:
        fd = os.open(data_path, os.O_RDONLY)
    except OSError:
        return None
    try:
        try:  # flush writeback so DONTNEED can actually evict (fsync on a
            os.fsync(fd)  # read-only fd works on Linux; best-effort)
        except OSError:
            pass
        dt = 0.0
        read_bytes = 0
        for i in range(n_clips):
            lo, hi, _ = ranges[i % len(ranges)]
            os.posix_fadvise(fd, lo, hi - lo, os.POSIX_FADV_DONTNEED)
            t0 = time.perf_counter()
            buf = os.pread(fd, hi - lo, lo)
            dt += time.perf_counter() - t0
            read_bytes += len(buf)
    except OSError:
        return None
    finally:
        os.close(fd)
    if dt <= 0:
        return None
    return {
        "cache_cold_clips_per_sec": round(n_clips / dt, 2),
        "cache_cold_mb_per_sec": round(read_bytes / dt / 1e6, 1),
        "cache_cold_note": ("span evicted (fadvise DONTNEED) before each "
                            "pread; eviction outside the timed region"),
    }


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("build", help="decode a manifest directory into a cache")
    b.add_argument("--data_dir", required=True)
    b.add_argument("--list", dest="list_file", default="",
                   help="build from a 'path label' list file instead of "
                        "scanning data_dir/{class}/ (manifest.from_list "
                        "format; relative paths resolve against data_dir)")
    b.add_argument("--out", required=True)
    b.add_argument("--fps", type=float, default=30.0)
    b.add_argument("--short_side", type=int, default=320)
    b.add_argument("--num_workers", type=int, default=8)
    m = sub.add_parser("bench", help="decode vs cache clips/sec microbench")
    m.add_argument("--data_dir", required=True)
    m.add_argument("--cache_dir", required=True)
    m.add_argument("--clip_duration", type=float, default=2.0)
    m.add_argument("--clips", type=int, default=64)
    m.add_argument("--num_workers", type=int, default=4)
    args = ap.parse_args(argv)

    if args.cmd == "build":
        manifest = None
        if args.list_file:
            from pytorchvideo_accelerate_tpu.data.manifest import from_list

            manifest = from_list(args.list_file, root=args.data_dir)
        index = build_cache(args.data_dir, args.out, fps=args.fps,
                            short_side=args.short_side,
                            num_workers=args.num_workers, manifest=manifest)
        total = sum(v["frames"] for v in index["videos"])
        size = os.path.getsize(os.path.join(args.out, DATA_NAME))
        print(f"cached {len(index['videos'])} videos, {total} frames, "
              f"{size / 1e9:.2f} GB -> {args.out}")
    else:
        print(json.dumps(bench_decode_vs_cache(
            args.data_dir, args.cache_dir, clip_duration=args.clip_duration,
            n_clips=args.clips, num_workers=args.num_workers)))


if __name__ == "__main__":
    main()
