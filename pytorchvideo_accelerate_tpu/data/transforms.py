"""Clip transform stack — numpy/cv2, host-side.

Reproduces the reference's transform factory `make_transform`
(run.py:68-102) exactly, as pure functions over (T, H, W, C) numpy frames
with explicit RNG:

  train: UniformTemporalSubsample(num_frames) -> Div255 ->
         Normalize(mean=0.45, std=0.225) ->
         RandomShortSideScale(256, 320) -> RandomCrop(256) ->
         RandomHorizontalFlip(0.5) [-> PackPathway(alpha)]
  val:   ... -> ShortSideScale(256) -> CenterCrop(256) [-> PackPathway]

Semantics notes (golden-tested in tests/test_transforms.py):
- UniformTemporalSubsample uses `linspace(0, T-1, n).long()` index truncation
  (pytorchvideo semantics via run.py:82 [external]).
- Short-side scale is bilinear (cv2.INTER_LINEAR, matching torch
  F.interpolate(mode="bilinear", align_corners=False) to ~1e-2 abs — parity
  asserted against installed torch-cpu in the tests).
- RandomShortSideScale samples an integer size uniformly in [min, max]
  inclusive.
- PackPathway (run.py:38-65): fast = all T frames, slow = index_select of
  T//alpha frames via the same truncated linspace.

Scaling/cropping runs before normalization would be cheaper (uint8 resize),
but the reference normalizes first — order preserved for exact behavioral
parity, and the fused fast path (`normalize_u8`) keeps it one allocation.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

try:  # cv2 ships its own ffmpeg; SURVEY §2.3-N9/N10 replacement
    import cv2
except Exception:  # pragma: no cover - cv2 is present in the build env
    cv2 = None


def uniform_temporal_subsample(frames: np.ndarray, num_samples: int) -> np.ndarray:
    """Evenly-spaced temporal subsample, truncated-linspace indices."""
    t = frames.shape[0]
    idx = np.linspace(0, t - 1, num_samples).astype(np.int64)
    return frames[idx]


def div255(frames: np.ndarray) -> np.ndarray:
    return frames.astype(np.float32) / 255.0


def normalize(frames: np.ndarray, mean: Sequence[float], std: Sequence[float]) -> np.ndarray:
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    return (frames - mean) / std


def normalize_u8(frames: np.ndarray, mean: Sequence[float],
                 std: Sequence[float]) -> np.ndarray:
    """Fused uint8 -> normalized float32: one allocation, two passes,
    algebraically `normalize(div255(x))` refactored as x*scale + bias
    (equal within float rounding, <=1e-6 abs; asserted in tests). The
    unfused pair costs 3 allocations/passes over every decoded clip —
    the eval/train host hot path (SURVEY §7 hard-part 1). Measured 1.5x
    faster at 32f x 256x320."""
    std32 = np.asarray(std, np.float32)
    scale = (1.0 / (255.0 * std32)).astype(np.float32)
    bias = (-np.asarray(mean, np.float32) / std32).astype(np.float32)
    out = np.multiply(frames, scale, dtype=np.float32)
    out += bias
    return out


def short_side_scale(frames: np.ndarray, size: int) -> np.ndarray:
    """Resize so the short spatial side == `size`, bilinear, AR preserved."""
    t, h, w = frames.shape[:3]
    # floor, matching pytorchvideo's ShortSideScale long-side math [external]
    if h <= w:
        new_h, new_w = size, int(np.floor(w * size / h))
    else:
        new_h, new_w = int(np.floor(h * size / w)), size
    if (new_h, new_w) == (h, w):
        return frames
    out = np.empty((t, new_h, new_w, frames.shape[3]), frames.dtype)
    for i in range(t):
        cv2.resize(frames[i], (new_w, new_h), dst=out[i], interpolation=cv2.INTER_LINEAR)
    return out


def random_short_side_scale(
    frames: np.ndarray, min_size: int, max_size: int, rng: np.random.Generator
) -> np.ndarray:
    size = int(rng.integers(min_size, max_size + 1))
    return short_side_scale(frames, size)


def center_crop(frames: np.ndarray, size: int) -> np.ndarray:
    h, w = frames.shape[1:3]
    top = (h - size) // 2
    left = (w - size) // 2
    return frames[:, top : top + size, left : left + size]


def uniform_crop(frames: np.ndarray, size: int, spatial_idx: int,
                 num_crops: int = 3) -> np.ndarray:
    """Crop `size`^2 at position `spatial_idx` of `num_crops` evenly-spaced
    positions along the LONGER spatial side (short side centered) —
    pytorchvideo `uniform_crop` semantics, the spatial half of the
    SlowFast/X3D papers' 30-view eval protocol (10 temporal x 3 spatial)."""
    h, w = frames.shape[1:3]
    if num_crops == 1:
        return center_crop(frames, size)

    def pos(delta):  # ceil spacing: 0, ceil(d/2), d at num_crops=3 — the
        # exact pytorchvideo uniform_crop offsets (their center is ceil,
        # 1px from center_crop's floor on odd deltas; parity wins)
        return int(np.ceil(delta * spatial_idx / (num_crops - 1)))

    # fixed (short) axis: pytorchvideo ceil-centers it — 1px from
    # center_crop's floor on odd deltas; parity wins
    if h <= w:  # landscape: slide along width
        top = int(np.ceil((h - size) / 2))
        left = pos(w - size)
    else:  # portrait: slide along height
        top = pos(h - size)
        left = int(np.ceil((w - size) / 2))
    return frames[:, top : top + size, left : left + size]


def random_crop(frames: np.ndarray, size: int, rng: np.random.Generator) -> np.ndarray:
    h, w = frames.shape[1:3]
    top = int(rng.integers(0, h - size + 1))
    left = int(rng.integers(0, w - size + 1))
    return frames[:, top : top + size, left : left + size]


def horizontal_flip(frames: np.ndarray, p: float, rng: np.random.Generator) -> np.ndarray:
    if rng.random() < p:
        return frames[:, :, ::-1]
    return frames


def pack_pathway(frames: np.ndarray, alpha: int) -> Dict[str, np.ndarray]:
    """SlowFast dual-rate packing (reference PackPathway, run.py:56-65):
    fast keeps all T frames; slow takes T//alpha truncated-linspace picks."""
    t = frames.shape[0]
    slow_idx = np.linspace(0, t - 1, t // alpha).astype(np.int64)
    return {"slow": frames[slow_idx], "fast": frames}


def make_transform(
    num_frames: int = 8,
    training: bool = False,
    is_slowfast: bool = False,
    slowfast_alpha: int = 4,
    min_short_side_scale: int = 256,
    max_short_side_scale: int = 320,
    crop_size: int = 256,
    mean: Sequence[float] = (0.45, 0.45, 0.45),
    std: Sequence[float] = (0.225, 0.225, 0.225),
    horizontal_flip_p: float = 0.5,
    output_dtype: str = "float32",
    num_spatial_crops: int = 1,
) -> Callable[[np.ndarray, Optional[np.random.Generator]], Dict[str, np.ndarray]]:
    """Build the full clip transform (reference make_transform, run.py:68-102).

    Returns `fn(frames_uint8_THWC, rng) -> {"video": ...}` or
    `{"slow": ..., "fast": ...}` (contiguous).

    `num_spatial_crops > 1` (eval only): the transform takes an extra
    `spatial_idx` argument selecting one of the evenly-spaced crops along
    the longer side (`uniform_crop`); `sample_views` multiplies temporal
    views by these spatial views — the papers' 30-view protocol is
    `eval_num_clips=10` x `eval_num_spatial_crops=3`. The callable's view
    count is exposed as `fn.num_spatial_crops`.

    `output_dtype="bfloat16"` casts the final clip on the host: the model
    casts inputs to its compute dtype anyway (models/common.py), so the cast
    loses nothing numerically while halving host-RAM, shm-ring, and
    host->HBM transfer bytes — the transfer is the input-bound regime's
    bottleneck at 32f/256^2 batches (~250 MB/step fp32).

    `output_dtype="uint8"` goes further (4x less than fp32): normalization
    is SKIPPED on the host and the geometric ops run on raw uint8 — the
    jitted step applies `x*scale + bias` on device, where XLA fuses it
    into the first conv's input read (trainer/steps.py device_normalize).
    Bilinear resize commutes with the affine normalize, so the only
    numeric delta vs the fp32 path is the resize's round-to-integer
    (±0.5/255 ≈ 0.009σ at the reference std) — the returned callable
    exposes `device_normalize = (mean, std)` so the trainer can finish
    the job in-graph.
    """
    u8_through = output_dtype == "uint8"
    if u8_through:
        out_dtype = np.uint8
    elif output_dtype == "float32":
        out_dtype = np.float32
    else:
        import ml_dtypes  # jax dependency, always present

        out_dtype = np.dtype(getattr(ml_dtypes, output_dtype))

    if num_spatial_crops < 1:
        raise ValueError(f"num_spatial_crops must be >= 1, got {num_spatial_crops}")
    if training and num_spatial_crops != 1:
        raise ValueError("num_spatial_crops is an eval-only option")

    def _precrop_eval(frames: np.ndarray) -> np.ndarray:
        x = uniform_temporal_subsample(frames, num_frames)
        if not u8_through:
            x = normalize_u8(x, mean, std)
        return short_side_scale(x, min_short_side_scale)

    def _finalize(x: np.ndarray) -> Dict[str, np.ndarray]:
        # astype on a sliced view already allocates contiguous output, so
        # cast first: one copy total in both modes
        if is_slowfast:
            out = pack_pathway(x, slowfast_alpha)
            return {k: np.ascontiguousarray(v.astype(out_dtype, copy=False))
                    for k, v in out.items()}
        return {"video": np.ascontiguousarray(x.astype(out_dtype, copy=False))}

    def transform(frames: np.ndarray,
                  rng: Optional[np.random.Generator] = None,
                  spatial_idx: Optional[int] = None):
        if training and rng is None:
            raise ValueError("training transform requires an rng")
        if training:
            x = uniform_temporal_subsample(frames, num_frames)
            if not u8_through:
                x = normalize_u8(x, mean, std)
            x = random_short_side_scale(
                x, min_short_side_scale, max_short_side_scale, rng
            )
            x = random_crop(x, crop_size, rng)
            x = horizontal_flip(x, horizontal_flip_p, rng)
        else:
            x = _precrop_eval(frames)
            if num_spatial_crops > 1:
                # no index given -> CENTER crop, matching what the same
                # call returns on a single-crop transform (not a silent
                # left-edge crop)
                x = uniform_crop(
                    x, crop_size,
                    num_spatial_crops // 2 if spatial_idx is None
                    else spatial_idx,
                    num_spatial_crops)
            else:
                x = center_crop(x, crop_size)
        return _finalize(x)

    if num_spatial_crops > 1:
        def spatial_views(frames: np.ndarray):
            """All spatial crops of one span, sharing ONE pre-crop pass
            (subsample/normalize/scale dominate eval host cost — running
            them per crop would triple the hot path)."""
            x = _precrop_eval(frames)
            return [_finalize(uniform_crop(x, crop_size, j, num_spatial_crops))
                    for j in range(num_spatial_crops)]

        transform.spatial_views = spatial_views
    transform.num_spatial_crops = num_spatial_crops
    # u8-through clips still need `x*scale + bias` — on device, in-graph
    # (trainer/steps.py); None means the host already normalized
    transform.device_normalize = (tuple(mean), tuple(std)) if u8_through else None
    return transform
