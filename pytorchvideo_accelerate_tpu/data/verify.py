"""Dataset doctor: audit a video tree before training on it.

Real Kinetics downloads always contain unreadable/truncated files. The
training pipeline substitutes them at runtime (pipeline.VideoClipSource),
but a pre-flight audit answers the questions substitution can't: HOW MANY
files are bad (a few is noise; 10% is a broken download), whether any
class is empty or too short for the configured clip duration, and the
fps/duration spread the clip samplers will see.

CLI:
    python -m pytorchvideo_accelerate_tpu.data.verify DATA_DIR/train \
        [--clip_duration 2.13] [--num_workers 8] [--deep]

`--deep` decodes one frame from the middle of every file (catches
truncated payloads that probe() alone misses); default is header probes
only. Prints a JSON report; exit code 1 when any file is unreadable, 2
when a class is empty — scriptable as a CI/pre-submit gate.
"""

from __future__ import annotations

import json
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from pytorchvideo_accelerate_tpu.data import decode as decode_mod
from pytorchvideo_accelerate_tpu.data.manifest import scan_directory


def check_one(path: str, deep: bool) -> dict:
    """Probe (and under `deep`, mid-file decode) one video."""
    try:
        meta = decode_mod.probe(path)
        if meta.frame_count <= 0:
            return {"path": path, "ok": False,
                    "error": f"empty stream (frames={meta.frame_count})"}
        if meta.fps <= 0:  # corrupt header: frames exist but fps is 0/bogus
            return {"path": path, "ok": False,  # (would div-by-zero below)
                    "error": f"unreadable header (fps={meta.fps})"}
        if deep:
            # decode_span raises on truncated payloads the header-only
            # probe can't see; the except below reports it
            mid = meta.duration / 2
            decode_mod.decode_span(path, mid, mid + 1.0 / meta.fps)
        return {"path": path, "ok": True, "fps": round(meta.fps, 3),
                "duration_s": round(meta.duration, 3)}
    except decode_mod.DECODE_ERRORS as e:
        return {"path": path, "ok": False,
                "error": f"{type(e).__name__}: {e}"}


def verify_tree(split_dir: str, clip_duration: float = 0.0,
                num_workers: int = 8, deep: bool = False,
                manifest=None) -> dict:
    """Audit every video under `split_dir`; returns the report dict."""
    manifest = manifest or scan_directory(split_dir)
    pool = ThreadPoolExecutor(max_workers=max(num_workers, 1))
    try:
        results = list(pool.map(lambda e: check_one(e.path, deep),
                                manifest.entries))
    finally:
        pool.shutdown(wait=False)

    bad = [r for r in results if not r["ok"]]
    ok = [r for r in results if r["ok"]]
    per_class = {name: 0 for name in manifest.class_names}
    short = []
    for entry, r in zip(manifest.entries, results):
        if r["ok"]:
            per_class[manifest.class_names[entry.label]] += 1
            if clip_duration and r["duration_s"] < clip_duration:
                short.append({"path": entry.path,
                              "duration_s": r["duration_s"]})
    empty_classes = sorted(n for n, c in per_class.items() if c == 0)
    durations = sorted(r["duration_s"] for r in ok)

    def pct(p):
        return durations[min(int(p * len(durations)), len(durations) - 1)]

    report = {
        "split_dir": split_dir,
        "num_videos": len(manifest),
        "num_classes": manifest.num_classes,
        "readable": len(ok),
        "unreadable": len(bad),
        "unreadable_files": [{"path": b["path"], "error": b["error"]}
                             for b in bad],
        "empty_classes": empty_classes,
        "deep": deep,
    }
    if durations:
        report["duration_s"] = {"min": durations[0], "p50": pct(0.5),
                                "p95": pct(0.95), "max": durations[-1]}
    if clip_duration:
        report["clip_duration"] = clip_duration
        # shorter-than-clip videos still train (the sampler clamps the
        # span and decode returns what exists) but with repeated content
        report["shorter_than_clip"] = short
    return report


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("split_dir")
    ap.add_argument("--list", dest="list_file", default="",
                    help="audit a 'path label' list file instead of "
                         "scanning split_dir/{class}/ (from_list format; "
                         "relative paths resolve against split_dir)")
    ap.add_argument("--clip_duration", type=float, default=0.0,
                    help="flag videos shorter than this many seconds")
    ap.add_argument("--num_workers", type=int, default=8)
    ap.add_argument("--deep", action="store_true",
                    help="also decode one mid-file frame per video")
    args = ap.parse_args(argv)

    manifest = None
    if args.list_file:
        from pytorchvideo_accelerate_tpu.data.manifest import from_list

        manifest = from_list(args.list_file, root=args.split_dir)
    report = verify_tree(args.split_dir, args.clip_duration,
                         args.num_workers, args.deep, manifest=manifest)
    print(json.dumps(report, indent=1))
    if report["unreadable"]:
        return 1
    if report["empty_classes"]:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
