// pva_native: native runtime pieces of the data loader (SURVEY §2.3-N8).
//
// The reference's loader runtime is torch's C/C++ substrate: CPython
// multiprocessing workers feeding pickled tensors through pipes plus
// cudaHostAlloc pinned staging (torch DataLoader num_workers=8/pin_memory,
// reference run.py:170-183). The TPU-native replacement keeps decode in
// worker *processes* (full GIL escape) but moves the transport into a
// process-shared ring buffer in POSIX shared memory: workers write decoded
// clip bytes straight into a slot; the trainer process maps the same pages
// and assembles batches with a multithreaded gather-copy. No serialization,
// no pipe syscalls per sample, no per-batch allocations.
//
// Synchronization: one PTHREAD_PROCESS_SHARED mutex + two condvars in the
// shm header guard a free-list and a ready-queue of slot ids. All waits are
// timed (robust against a dead peer; callers retry/abort on timeout).
//
// Built with plain g++ -shared (no external deps); loaded via ctypes
// (pytorchvideo_accelerate_tpu/native/__init__.py). Layout is
// single-machine, same-architecture — not a wire format.

#include <pthread.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kMagic = 0x70766172696E6731ULL;  // "pvaring1"
constexpr uint32_t kAlign = 64;

struct Header {
  uint64_t magic;
  uint32_t n_slots;
  uint64_t slot_bytes;
  uint64_t data_off;   // byte offset of slot 0 from base
  uint64_t meta_off;   // byte offset of per-slot meta arrays
  pthread_mutex_t mu;
  pthread_cond_t cv_free;
  pthread_cond_t cv_ready;
  // ring of free slot ids and ring of ready slot ids
  uint32_t free_head, free_count;
  uint32_t ready_head, ready_count;
  uint32_t shutdown;
};

struct SlotMeta {
  uint64_t nbytes;
  uint64_t tag;
};

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~uint64_t(kAlign - 1); }

inline Header* hdr(void* base) { return reinterpret_cast<Header*>(base); }
inline uint32_t* free_ring(void* base, Header* h) {
  return reinterpret_cast<uint32_t*>(static_cast<char*>(base) + sizeof(Header));
}
inline uint32_t* ready_ring(void* base, Header* h) {
  return free_ring(base, h) + h->n_slots;
}
inline SlotMeta* metas(void* base, Header* h) {
  return reinterpret_cast<SlotMeta*>(static_cast<char*>(base) + h->meta_off);
}

void abstime_in(timespec* ts, int timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

}  // namespace

extern "C" {

// Total shm bytes needed for a ring of n_slots x slot_bytes.
uint64_t pva_rb_total_size(uint32_t n_slots, uint64_t slot_bytes) {
  uint64_t off = align_up(sizeof(Header) + 2ULL * n_slots * sizeof(uint32_t));
  uint64_t meta = align_up(off + n_slots * sizeof(SlotMeta));
  return meta + n_slots * align_up(slot_bytes);
}

// Initialize a ring in (zeroed) shared memory. Parent-process only, once.
int pva_rb_init(void* base, uint32_t n_slots, uint64_t slot_bytes) {
  Header* h = hdr(base);
  h->magic = kMagic;
  h->n_slots = n_slots;
  h->slot_bytes = align_up(slot_bytes);
  uint64_t rings_end = sizeof(Header) + 2ULL * n_slots * sizeof(uint32_t);
  h->meta_off = align_up(rings_end);
  h->data_off = align_up(h->meta_off + n_slots * sizeof(SlotMeta));
  h->free_head = 0;
  h->free_count = n_slots;
  h->ready_head = 0;
  h->ready_count = 0;
  h->shutdown = 0;
  uint32_t* fr = free_ring(base, h);
  for (uint32_t i = 0; i < n_slots; ++i) fr[i] = i;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  if (pthread_mutex_init(&h->mu, &ma) != 0) return -1;
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  if (pthread_cond_init(&h->cv_free, &ca) != 0) return -1;
  if (pthread_cond_init(&h->cv_ready, &ca) != 0) return -1;
  return 0;
}

void* pva_rb_slot_ptr(void* base, uint32_t slot) {
  Header* h = hdr(base);
  return static_cast<char*>(base) + h->data_off + uint64_t(slot) * align_up(h->slot_bytes);
}

uint64_t pva_rb_slot_bytes(void* base) { return hdr(base)->slot_bytes; }

// Producer: take a free slot (blocks up to timeout_ms). -1 timeout, -2 shutdown.
int pva_rb_acquire(void* base, int timeout_ms) {
  Header* h = hdr(base);
  timespec ts;
  abstime_in(&ts, timeout_ms);
  pthread_mutex_lock(&h->mu);
  while (h->free_count == 0 && !h->shutdown) {
    if (pthread_cond_timedwait(&h->cv_free, &h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->shutdown) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  uint32_t slot = free_ring(base, h)[h->free_head];
  h->free_head = (h->free_head + 1) % h->n_slots;
  h->free_count--;
  pthread_mutex_unlock(&h->mu);
  return static_cast<int>(slot);
}

// Producer: publish a filled slot.
int pva_rb_commit(void* base, uint32_t slot, uint64_t nbytes, uint64_t tag) {
  Header* h = hdr(base);
  SlotMeta* m = metas(base, h);
  m[slot].nbytes = nbytes;
  m[slot].tag = tag;
  pthread_mutex_lock(&h->mu);
  uint32_t pos = (h->ready_head + h->ready_count) % h->n_slots;
  ready_ring(base, h)[pos] = slot;
  h->ready_count++;
  pthread_cond_signal(&h->cv_ready);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Consumer: pop the oldest ready slot. -1 timeout, -2 shutdown+drained.
int pva_rb_pop(void* base, int timeout_ms, uint64_t* nbytes, uint64_t* tag) {
  Header* h = hdr(base);
  timespec ts;
  abstime_in(&ts, timeout_ms);
  pthread_mutex_lock(&h->mu);
  while (h->ready_count == 0) {
    if (h->shutdown) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    if (pthread_cond_timedwait(&h->cv_ready, &h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  uint32_t slot = ready_ring(base, h)[h->ready_head];
  h->ready_head = (h->ready_head + 1) % h->n_slots;
  h->ready_count--;
  pthread_mutex_unlock(&h->mu);
  SlotMeta* m = metas(base, h);
  if (nbytes) *nbytes = m[slot].nbytes;
  if (tag) *tag = m[slot].tag;
  return static_cast<int>(slot);
}

// Consumer: return a drained slot to the free list.
int pva_rb_release(void* base, uint32_t slot) {
  Header* h = hdr(base);
  pthread_mutex_lock(&h->mu);
  uint32_t pos = (h->free_head + h->free_count) % h->n_slots;
  free_ring(base, h)[pos] = slot;
  h->free_count++;
  pthread_cond_signal(&h->cv_free);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Wake all waiters permanently (loader close / worker exit signal).
void pva_rb_shutdown(void* base) {
  Header* h = hdr(base);
  pthread_mutex_lock(&h->mu);
  h->shutdown = 1;
  pthread_cond_broadcast(&h->cv_free);
  pthread_cond_broadcast(&h->cv_ready);
  pthread_mutex_unlock(&h->mu);
}

uint32_t pva_rb_ready_count(void* base) {
  Header* h = hdr(base);
  pthread_mutex_lock(&h->mu);
  uint32_t c = h->ready_count;
  pthread_mutex_unlock(&h->mu);
  return c;
}

// Multithreaded gather-copy: dst[off[i] : off[i]+sizes[i]] = *srcs[i].
// Batch assembly without the GIL (ctypes releases it for the call); items
// are striped over threads by cumulative size.
int pva_gather_copy(char* dst, const char** srcs, const uint64_t* offs,
                    const uint64_t* sizes, uint32_t n, uint32_t n_threads) {
  if (n == 0) return 0;
  if (n_threads <= 1 || n == 1) {
    for (uint32_t i = 0; i < n; ++i) memcpy(dst + offs[i], srcs[i], sizes[i]);
    return 0;
  }
  uint64_t total = 0;
  for (uint32_t i = 0; i < n; ++i) total += sizes[i];
  uint64_t per = total / n_threads + 1;
  std::vector<std::thread> threads;
  uint32_t i = 0;
  for (uint32_t t = 0; t < n_threads && i < n; ++t) {
    uint64_t budget = 0;
    uint32_t start = i;
    while (i < n && budget < per) budget += sizes[i++];
    threads.emplace_back([=]() {
      for (uint32_t j = start; j < i; ++j) memcpy(dst + offs[j], srcs[j], sizes[j]);
    });
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
