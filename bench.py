#!/usr/bin/env python
"""Benchmark harness: clips/sec/chip on the reference training workloads.

Prints exactly ONE JSON line to stdout:
    {"metric": "...", "value": N, "unit": "clips/sec/chip", "vs_baseline": N,
     "mfu": ..., "tflops_per_sec": ..., "step_ms_blocked": ..., "models": {...}}
(everything else goes to stderr). Runs on the attached TPU by default; pass
--smoke for a CPU-sized sanity run.

Headline workload matches the reference launch recipe
(run_slowfast_r50.sh:3-12, SURVEY §6): SlowFast-R50, 32 frames, 256^2 crops,
batch 8 per chip, bf16 compute (standing in for the recipe's fp16 AMP),
measuring the compiled train step (fwd+bwd+update, BN stats, metrics) end to
end. The BASELINE configs 2/4/5 (x3d_s, mvit_b, videomae_b_pretrain) are
benched too and reported under "models".

Self-audit (so impossible numbers can't pass unremarked):
- per-step FLOPs come from XLA's own `compiled.cost_analysis()`;
- achieved TFLOP/s and MFU are derived from the *blocked* per-step latency
  (each step synced before the next dispatch — no async-dispatch inflation);
- the pipelined throughput loop rotates distinct batches so a
  constant-folding/caching runtime can't replay one result;
- if pipelined step time is <50%% of blocked step time, OR the implied MFU
  exceeds 100%% of the chip's bf16 peak, the run is flagged
  ("suspect": true) — the platform isn't executing with real device timing.
"""

import argparse
import json
import math
import os
import statistics
import subprocess
import sys
import time


def log(*args):
    print(*args, file=sys.stderr, flush=True)


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from pytorchvideo_accelerate_tpu.utils.hw import peak_tflops  # noqa: E402


# Benchmark workloads: BASELINE.md configs. (model, frames, crop, per-chip
# batch, pretraining?). x3d_s samples 13f@160 (BASELINE config 2), mvit_b and
# videomae_b use 16f@224 (configs 4/5).
WORKLOADS = {
    "slowfast_r50": dict(num_frames=32, crop=256, batch_size=8, pretrain=False),
    "x3d_s": dict(num_frames=13, crop=160, batch_size=8, pretrain=False),
    "mvit_b": dict(num_frames=16, crop=224, batch_size=8, pretrain=False),
    "videomae_b_pretrain": dict(num_frames=16, crop=224, batch_size=8,
                                pretrain=True),
}


def bench_model(name: str, wl: dict, args, mesh, n_chips: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorchvideo_accelerate_tpu.config import ModelConfig, OptimConfig
    from pytorchvideo_accelerate_tpu.models import create_model
    from pytorchvideo_accelerate_tpu.parallel.sharding import shard_batch
    from pytorchvideo_accelerate_tpu.trainer import (
        TrainState, build_optimizer, make_pretrain_step, make_train_step,
    )

    frames, crop, bsz = wl["num_frames"], wl["crop"], wl["batch_size"]
    if args.smoke:
        frames, crop, bsz = max(frames // 4, 4), 64, 2
        if name == "videomae_b_pretrain":
            crop = 64  # tubelet 16 divides
    num_classes = 700  # Kinetics-700 (BASELINE.json metric)
    model_cfg = ModelConfig(name=name, num_classes=num_classes,
                            slowfast_alpha=args.alpha)
    model = create_model(model_cfg, "bf16")

    B = bsz * n_chips  # global batch: bench batch is per chip
    rng = np.random.default_rng(0)

    def make_batch(seed):
        r = np.random.default_rng(seed)
        if name.startswith("slowfast"):
            b = {
                "slow": r.standard_normal(
                    (B, frames // args.alpha, crop, crop, 3), dtype=np.float32),
                "fast": r.standard_normal(
                    (B, frames, crop, crop, 3), dtype=np.float32),
            }
        else:
            b = {"video": r.standard_normal(
                (B, frames, crop, crop, 3), dtype=np.float32)}
        if not wl["pretrain"]:
            b["label"] = r.integers(0, num_classes, B).astype(np.int32)
        return b

    batch = make_batch(0)
    if name.startswith("slowfast"):
        sample = (jnp.zeros((1, *batch["slow"].shape[1:])),
                  jnp.zeros((1, *batch["fast"].shape[1:])))
    else:
        sample = jnp.zeros((1, *batch["video"].shape[1:]))

    log(f"[{name}] global batch {B} ({bsz}/chip), {frames} frames @ {crop}^2")

    variables = model.init(jax.random.key(0), sample)
    tx = build_optimizer(OptimConfig(), total_steps=args.steps + args.warmup)
    state = TrainState.create(variables["params"],
                              variables.get("batch_stats", {}), tx)
    if wl["pretrain"]:
        step = make_pretrain_step(model, tx, mesh)
    else:
        step = make_train_step(model, tx, mesh)

    # two distinct device batches, rotated through the timing loop
    gbs = [shard_batch(mesh, batch), shard_batch(mesh, make_batch(1))]

    # --- compile + XLA's own FLOPs estimate -------------------------------
    t0 = time.perf_counter()
    lowered = step.lower(state, gbs[0], jax.random.key(0))
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    flops_per_step = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops_per_step = float(ca.get("flops", 0.0)) or None
    except Exception as e:  # cost_analysis availability varies by backend
        log(f"[{name}] cost_analysis unavailable: {e}")
    log(f"[{name}] compile: {compile_s:.1f}s, "
        f"flops/step: {flops_per_step and f'{flops_per_step / 1e12:.2f}T'}")

    for i in range(max(args.warmup, 1)):  # >=1: later loops read `metrics`
        state, metrics = compiled(state, gbs[i % 2], jax.random.key(i))
    jax.block_until_ready(metrics["loss"])

    # --- blocked per-step latency (the honest number) ---------------------
    blocked = []
    for i in range(args.steps):
        t0 = time.perf_counter()
        state, metrics = compiled(state, gbs[i % 2], jax.random.key(50 + i))
        jax.block_until_ready(metrics["loss"])
        blocked.append(time.perf_counter() - t0)
    blocked_ms = statistics.median(blocked) * 1e3

    # --- pipelined throughput (async dispatch, one sync at the end) -------
    t0 = time.perf_counter()
    for i in range(args.steps):
        state, metrics = compiled(state, gbs[i % 2], jax.random.key(100 + i))
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0
    pipelined_ms = dt / args.steps * 1e3

    clips_per_sec = B * args.steps / dt
    per_chip = clips_per_sec / n_chips
    suspect = pipelined_ms < 0.5 * blocked_ms

    dev = jax.devices()[0]
    peak = peak_tflops(dev)
    tflops = mfu = None
    if flops_per_step:
        tflops = flops_per_step / (blocked_ms / 1e3) / 1e12 / n_chips
        if peak:
            mfu = tflops / peak
            if mfu > 1.0:  # >100% of bf16 peak is physically impossible:
                suspect = True  # the platform isn't timing real execution
                # (e.g. a forwarding backend acking block_until_ready early)
    log(f"[{name}] {args.steps} steps: blocked {blocked_ms:.1f} ms/step, "
        f"pipelined {pipelined_ms:.1f} ms/step -> {per_chip:.2f} clips/s/chip"
        f"{f', {tflops:.1f} TFLOP/s/chip' if tflops else ''}"
        f"{f', MFU {mfu:.1%}' if mfu else ''}"
        f"{' SUSPECT (device timing not trustworthy)' if suspect else ''}, "
        f"final loss {float(metrics['loss']):.3f}")

    out = {
        "clips_per_sec_per_chip": round(per_chip, 3),
        "step_ms_blocked": round(blocked_ms, 3),
        "step_ms_pipelined": round(pipelined_ms, 3),
        "compile_s": round(compile_s, 1),
        "batch_per_chip": bsz,
        "frames": frames,
        "crop": crop,
        "suspect": suspect,
    }
    if flops_per_step:
        out["flops_per_step"] = flops_per_step
        out["tflops_per_sec_per_chip"] = round(tflops, 2)
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
    return out


def bench_data(args) -> dict:
    """Host input-pipeline microbench (SURVEY §7 hard-part 1): encodes a
    small synthetic video tree, then measures raw cv2 decode vs pre-decoded
    cache clips/sec and ClipLoader end-to-end throughput on both transports.

    These numbers are host-CPU-real — trustworthy on any box, including when
    device timing is not — and they bound the chips/host ratio the input
    pipeline can feed."""
    import shutil
    import tempfile

    import numpy as np

    try:
        import cv2
    except ImportError:
        return {"error": "cv2 unavailable"}

    from pytorchvideo_accelerate_tpu.data.cache import (
        bench_decode_vs_cache, build_cache,
    )
    from pytorchvideo_accelerate_tpu.data.manifest import scan_directory
    from pytorchvideo_accelerate_tpu.data.pipeline import (
        ClipLoader, VideoClipSource,
    )
    from pytorchvideo_accelerate_tpu.data.transforms import make_transform

    tmp = tempfile.mkdtemp(prefix="pva_bench_data_")
    fps = 30.0
    n_videos, n_frames = (4, 24) if args.smoke else (8, 64)
    w_px, h_px = (96, 64) if args.smoke else (320, 256)
    crop = 64 if args.smoke else 224
    num_frames = 8
    clip_duration = num_frames * 2 / fps  # sampling_rate 2
    out: dict = {"video_px": f"{w_px}x{h_px}", "num_videos": n_videos}
    rng = np.random.default_rng(0)
    try:
        root = os.path.join(tmp, "train")
        for c in range(2):
            cls = os.path.join(root, f"class{c}")
            os.makedirs(cls)
            for v in range(n_videos // 2):
                wr = cv2.VideoWriter(
                    os.path.join(cls, f"v{v}.mp4"),
                    cv2.VideoWriter_fourcc(*"mp4v"), fps, (w_px, h_px))
                if not wr.isOpened():
                    return {"error": "mp4v codec unavailable"}
                for _ in range(n_frames):
                    wr.write(rng.integers(0, 255, (h_px, w_px, 3), np.uint8))
                wr.release()

        cache_dir = os.path.join(tmp, "cache")
        t0 = time.perf_counter()
        build_cache(root, cache_dir, fps=fps, short_side=min(h_px, w_px),
                    num_workers=2)
        out["cache_build_s"] = round(time.perf_counter() - t0, 2)
        out.update(bench_decode_vs_cache(
            root, cache_dir, clip_duration=clip_duration,
            n_clips=16 if args.smoke else 48, num_workers=2))

        # loader end-to-end: decode + transforms + batch assembly
        tf = make_transform(num_frames=num_frames, training=True,
                            min_short_side_scale=crop,
                            max_short_side_scale=crop + 16, crop_size=crop)
        manifest = scan_directory(root)
        epochs = 2 if args.smoke else 4
        for transport in ("thread", "process"):
            src = VideoClipSource(manifest, tf, clip_duration, training=True,
                                  seed=0)
            loader = ClipLoader(src, global_batch_size=4, shuffle=True,
                                num_workers=2, transport=transport)
            try:
                clips = 0
                next(iter(loader.epoch(0)))  # warm pools/caches
                t0 = time.perf_counter()
                for ep in range(1, epochs + 1):
                    for batch in loader.epoch(ep):
                        clips += batch["label"].shape[0]
                dt = time.perf_counter() - t0
                key = f"loader_{transport}_clips_per_sec"
                out[key] = round(clips / dt, 2)
                if loader.transport != transport:  # native lib unavailable
                    out[key + "_note"] = f"fell back to {loader.transport}"
            finally:
                loader.close()
        log(f"[data] {out}")
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default="all",
                    help="comma list of " + ",".join(WORKLOADS) + " or 'all'")
    ap.add_argument("--alpha", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--trainer", action="store_true",
                    help="also run Trainer.fit() on synthetic data and report "
                         "its throughput vs the raw step (hot-loop overhead)")
    ap.add_argument("--data", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="host input-pipeline microbench (decode vs cache vs "
                         "loader clips/sec; CPU-real numbers regardless of "
                         "device-timing trustworthiness); --no-data skips")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-safe shapes for harness verification")
    ap.add_argument("--per_model_timeout", type=int, default=900,
                    help="seconds before a model's bench is abandoned "
                         "(a wedged compile/backend must not prevent the "
                         "final JSON line; 0 = no limit)")
    args = ap.parse_args()

    # The axon tunnel to the chip can wedge at backend init (observed: device
    # enumeration blocks forever, hanging any process that touches it). Probe
    # reachability in a DISPOSABLE subprocess first: if it can't enumerate
    # devices in time, fall back to CPU smoke shapes and say so in the JSON
    # line instead of timing out with no output at all.
    tpu_unreachable = False
    if not args.smoke:
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=240, check=True,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
        except Exception as e:
            tpu_unreachable = True
            args.smoke = True
            log(f"TPU backend unreachable ({type(e).__name__}); falling back "
                "to CPU smoke shapes — numbers are NOT device numbers")

    import jax

    if args.smoke:
        args.steps, args.warmup = 3, 1
        jax.config.update("jax_platforms", "cpu")
    # persistent compilation cache: pays off every driver re-run/restart
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        log(f"compilation cache unavailable: {e}")

    from pytorchvideo_accelerate_tpu.config import MeshConfig
    from pytorchvideo_accelerate_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    n_chips = len(devices)
    peak = peak_tflops(devices[0])
    log(f"devices: {n_chips} x {devices[0].device_kind} "
        f"({devices[0].platform}), bf16 peak "
        f"{f'{peak:.0f} TFLOP/s/chip' if peak else 'unknown'}")
    mesh = make_mesh(MeshConfig(), devices=devices)

    names = list(WORKLOADS) if args.models == "all" else args.models.split(",")
    results = {}

    # BaseException: must NOT be swallowed by any `except Exception` inside
    # bench_model (e.g. the cost_analysis guard) — only the model-loop
    # handler below may consume it
    class _Timeout(BaseException):
        pass

    import signal

    def _alarm(signum, frame):
        raise _Timeout(f"exceeded --per_model_timeout={args.per_model_timeout}s")

    can_alarm = hasattr(signal, "SIGALRM") and args.per_model_timeout > 0
    if can_alarm:
        signal.signal(signal.SIGALRM, _alarm)

    # Last-resort watchdog for hangs that SIGALRM can't interrupt (a wedged
    # compile inside a GIL-holding C call): after the total budget, emit the
    # final JSON with whatever finished and hard-exit — the driver must
    # always get the one-line result.
    import threading

    emitted = threading.Event()
    emit_lock = threading.Lock()
    extras: dict = {}

    def emit_final():
        with emit_lock:  # exactly ONE JSON line, even racing the watchdog
            if emitted.is_set():
                return
            emitted.set()
        print(json.dumps(finalize(results, extras, args, tpu_unreachable)))
        sys.stdout.flush()

    watchdog_timer = None
    if can_alarm:
        total_budget = args.per_model_timeout * (len(names) + 1)

        def watchdog():
            for name in names:  # mark whatever never finished
                results.setdefault(name, {"error": "total watchdog timeout"})
            extras["error"] = f"watchdog: exceeded {total_budget}s total"
            log(extras["error"])
            emit_final()
            os._exit(2)

        watchdog_timer = threading.Timer(total_budget, watchdog)
        watchdog_timer.daemon = True
        watchdog_timer.start()

    for name in names:
        try:
            if can_alarm:
                signal.alarm(args.per_model_timeout)
            results[name] = bench_model(name, WORKLOADS[name], args, mesh,
                                        n_chips)
        except (Exception, _Timeout) as e:
            log(f"[{name}] FAILED: {type(e).__name__}: {e}")
            results[name] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            if can_alarm:
                signal.alarm(0)

    if args.trainer:
        try:
            extras["trainer_vs_rawstep"] = bench_trainer(args, results)
        except Exception as e:
            log(f"[trainer] FAILED: {type(e).__name__}: {e}")
            extras["trainer_error"] = f"{type(e).__name__}: {e}"
    if args.data:
        try:
            extras["data_pipeline"] = bench_data(args)
        except Exception as e:
            log(f"[data] FAILED: {type(e).__name__}: {e}")
            extras["data_pipeline"] = {"error": f"{type(e).__name__}: {e}"}
    if watchdog_timer is not None:
        watchdog_timer.cancel()
    emit_final()


def finalize(results: dict, extras: dict, args, tpu_unreachable: bool) -> dict:
    """Assemble the single JSON line from per-model results + extras."""
    flag_name = "slowfast_r50"
    flag = results.get(flag_name, {})
    if "clips_per_sec_per_chip" not in flag:  # flagship failed: next best
        flag_name, flag = next(
            ((n, r) for n, r in results.items()
             if "clips_per_sec_per_chip" in r), ("none", {}))

    baseline = None
    try:
        published = json.load(
            open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BASELINE.json"))).get("published", {})
        baseline = published.get("clips_per_sec_per_chip")
    except Exception:
        pass
    value = flag.get("clips_per_sec_per_chip", 0.0)
    vs = value / baseline if baseline else 1.0

    out = {
        "metric": f"train clips/sec/chip ({flag_name}, "
                  f"{flag.get('frames', '?')}f, {flag.get('crop', '?')}px, "
                  "bf16" + (", smoke" if args.smoke else "") + ")",
        "value": value,
        "unit": "clips/sec/chip",
        "vs_baseline": round(vs, 3),
        "step_ms_blocked": flag.get("step_ms_blocked"),
        "tflops_per_sec": flag.get("tflops_per_sec_per_chip"),
        "mfu": flag.get("mfu"),
        "suspect": flag.get("suspect"),
        "models": results,
    }
    tr = extras.get("trainer_vs_rawstep")
    if tr is not None:
        out["trainer_vs_rawstep"] = round(tr, 3)
    if "trainer_error" in extras:
        out["trainer_error"] = extras["trainer_error"]
    if "data_pipeline" in extras:
        out["data_pipeline"] = extras["data_pipeline"]
    if "error" in extras:
        out["error"] = extras["error"]
    if tpu_unreachable:
        out["suspect"] = True
        out["error"] = ("tpu backend init unreachable; CPU smoke fallback — "
                        "not device numbers")
    return out


def bench_trainer(args, results: dict) -> float | None:
    """Trainer.fit() on synthetic data vs the raw-step number — proves the
    hot loop doesn't sync away the pipelining (VERDICT r2 weak #4)."""
    import jax

    from pytorchvideo_accelerate_tpu.config import (
        DataConfig, ModelConfig, OptimConfig, TrainConfig,
    )
    from pytorchvideo_accelerate_tpu.trainer.loop import Trainer

    frames, crop, bsz = (8, 64, 2) if args.smoke else (32, 256, 8)
    n_videos = bsz * len(jax.devices()) * (4 if args.smoke else 16)
    cfg = TrainConfig(
        model=ModelConfig(name="slowfast_r50", num_classes=700),
        data=DataConfig(synthetic=True, synthetic_num_videos=n_videos,
                        num_frames=frames, crop_size=crop, batch_size=bsz,
                        num_workers=2, limit_val_batches=1),
        optim=OptimConfig(num_epochs=2),  # epoch 1 excludes compile
        mixed_precision="bf16",
    )
    tr = Trainer(cfg)
    res = tr.fit()
    # steady-state: train-section wall time of the post-compile epoch only
    # (excludes compile, eval, checkpointing — the quantity the raw-step
    # number measures)
    steps_per_epoch = res["steps"] // cfg.optim.num_epochs
    dt = res["epoch_train_times"][-1]
    clips = steps_per_epoch * bsz * len(jax.devices())
    fit_cps_chip = clips / dt / len(jax.devices())
    raw = (results.get("slowfast_r50") or {}).get("clips_per_sec_per_chip")
    log(f"[trainer] fit() steady-state epoch: {steps_per_epoch} steps in "
        f"{dt:.2f}s = {fit_cps_chip:.2f} clips/s/chip (incl. data pipeline)"
        + (f" = {fit_cps_chip / raw:.0%} of raw step" if raw else ""))
    return fit_cps_chip / raw if raw else None


if __name__ == "__main__":
    main()
