#!/usr/bin/env python
"""Benchmark harness: clips/sec/chip on the reference training workloads.

Prints exactly ONE compact JSON line (<=1.5 KB — the driver captures only a
~2 KB stdout tail) to stdout:
    {"metric": "...", "value": N, "unit": "clips/sec/chip", "vs_baseline": N,
     "mfu": ..., "tflops_per_sec": ..., "step_ms_blocked": ..., "suspect": B,
     "models": {name: clips_per_sec}, "probes": {run,round,ok,last}}
Full per-model dicts, probe timestamps, and the host data-pipeline blocks go
to bench_partial.json (flushed throughout the run); logs go to stderr. Runs
on the attached TPU by default; pass --smoke for a CPU-sized sanity run.

Headline workload matches the reference launch recipe
(run_slowfast_r50.sh:3-12, SURVEY §6): SlowFast-R50, 32 frames, 256^2 crops,
batch 8 per chip, bf16 compute (standing in for the recipe's fp16 AMP),
measuring the compiled train step (fwd+bwd+update, BN stats, metrics) end to
end. The BASELINE configs 2/4/5 (x3d_s, mvit_b, videomae_b_pretrain) are
benched too and reported under "models".

Wedge resilience (the axon tunnel to the chip can block forever at backend
init or mid-compile, and a kill can leave it wedged for hours — observed in
rounds 3-4):
- the PARENT process never touches devices (it forces the CPU platform);
  every device-facing bench runs in a DISPOSABLE CHILD subprocess with its
  own kill timeout, so one wedged compile loses one model, not the round;
- TPU reachability is probed in a subprocess before each device attempt and
  re-probed (with backoff) between models; every probe is timestamped into
  "probe_attempts" in the final JSON — the evidence trail for rounds where
  the device was unreachable throughout;
- models that had to fall back to CPU smoke are re-tried on the device at
  the end of the run if a late probe succeeds;
- partial results are flushed to bench_partial.json after every model.

Self-audit (so impossible numbers can't pass unremarked):
- per-step FLOPs come from XLA's own `compiled.cost_analysis()`;
- achieved TFLOP/s and MFU are derived from the *blocked* per-step latency
  (each step synced before the next dispatch — no async-dispatch inflation);
- the pipelined throughput loop rotates distinct batches so a
  constant-folding/caching runtime can't replay one result;
- if pipelined step time is <50%% of blocked step time, OR the implied MFU
  exceeds 100%% of the chip's bf16 peak, the run is flagged
  ("suspect": true) — the platform isn't executing with real device timing.
"""

import argparse
import datetime
import json
import math
import os
import signal
import statistics
import subprocess
import sys
import time


def log(*args):
    print(*args, file=sys.stderr, flush=True)


HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
from pytorchvideo_accelerate_tpu.utils.hw import (  # noqa: E402
    peak_tflops,
    resolve_peak,
)


# Benchmark workloads: BASELINE.md configs. (model, frames, crop, per-chip
# batch, pretraining?). x3d_s samples 13f@160 (BASELINE config 2), mvit_b and
# videomae_b use 16f@224 (configs 4/5).
WORKLOADS = {
    "slowfast_r50": dict(num_frames=32, crop=256, batch_size=8, pretrain=False),
    "x3d_s": dict(num_frames=13, crop=160, batch_size=8, pretrain=False),
    "mvit_b": dict(num_frames=16, crop=224, batch_size=8, pretrain=False),
    "videomae_b_pretrain": dict(num_frames=16, crop=224, batch_size=8,
                                pretrain=True),
    # r5 zoo additions — opt-in (--models), not in the default set: the
    # default bench covers the four BASELINE configs and every extra child
    # spends scarce tunnel-window minutes
    "r2plus1d_r50": dict(num_frames=16, crop=224, batch_size=8,
                         pretrain=False),
    "csn_r101": dict(num_frames=32, crop=224, batch_size=8, pretrain=False),
}

# the driver's plain `python bench.py` measures these (BASELINE configs);
# `--models all` or explicit names reach the rest of WORKLOADS
DEFAULT_MODELS = ("slowfast_r50", "x3d_s", "mvit_b", "videomae_b_pretrain")


def _scratch_outdir(tag: str) -> str:
    """Scratch output_dir for a bench lane's Trainer runs: flight
    records, trace rings, and checkpoints land here — NEVER the repo
    root (TrainConfig's default output_dir "."), whose generated
    flight_record.json used to re-churn ~1000 lines into the worktree
    every round. Left to the OS tempdir reaper: a post-crash record must
    survive long enough for pva-tpu-doctor --obs-dir to read it."""
    import tempfile

    return tempfile.mkdtemp(prefix=f"pva_bench_{tag}_")


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime("%FT%TZ")


def _setup_jax(smoke: bool, child: str | None = None):
    """Backend + persistent compile cache config (child processes and the
    device-free parent both go through here)."""
    import jax

    if smoke:
        jax.config.update("jax_platforms", "cpu")
    if child == "__stream__":
        # The persistent cache intermittently corrupts the native heap in
        # THIS child only ("free(): invalid pointer" / SIGSEGV inside the
        # trunk sub-lane's warmup_stream compiles — the lane's only
        # compiles slow enough to be serialized; ~half of runs with the
        # cache on, 0/10 with it off). Until that interaction is
        # understood, the stream child runs on in-process jit caches
        # alone; its post-warmup recompile count already proves flatness.
        return jax
    cache_dir = os.path.join(HERE, ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        log(f"compilation cache unavailable: {e}")
    return jax


def bench_model(name: str, wl: dict, args, n_chips: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorchvideo_accelerate_tpu.utils.bench_setup import (
        build_step_setup, fetch_loss, xla_flops,
    )

    frames, crop, bsz = wl["num_frames"], wl["crop"], wl["batch_size"]
    if args.smoke:
        frames, crop, bsz = max(frames // 4, 4), 64, 2
        if name == "videomae_b_pretrain":
            crop = 64  # tubelet 16 divides
    num_classes = 700  # Kinetics-700 (BASELINE.json metric)
    setup = build_step_setup(
        name, frames=frames, crop=crop, batch_per_chip=bsz,
        num_classes=num_classes, alpha=args.alpha, pretrain=wl["pretrain"],
        total_steps=args.steps + args.warmup,
        # raw-u8 batches (default, supervised): 4x less host->device
        # transfer during setup — the phase the 04:02Z wedge killed — with
        # the normalize affine fused into the step (the host_cast=u8
        # production path). --inputs f32 restores the r1-r4 staging (those
        # rounds' device numbers were all suspect, so no valid historical
        # series is broken); the effective mode is recorded per model.
        input_u8=args.inputs == "u8",
    )
    B, state = setup.global_batch, setup.state

    log(f"[{name}] global batch {B} ({bsz}/chip), {frames} frames @ {crop}^2")

    # two distinct device batches, rotated through the timing loop
    gbs = [setup.device_batch(0), setup.device_batch(1)]

    # --- compile + XLA's own FLOPs estimate -------------------------------
    t0 = time.perf_counter()
    compiled = setup.step.lower(state, gbs[0], jax.random.key(0)).compile()
    compile_s = time.perf_counter() - t0
    flops_per_step = xla_flops(compiled)
    log(f"[{name}] compile: {compile_s:.1f}s, "
        f"flops/step: {flops_per_step and f'{flops_per_step / 1e12:.2f}T'}")

    # Sync discipline: value-fetch, never block_until_ready (acked early
    # by the axon forwarder — see utils/bench_setup.fetch_loss)
    _fetch = fetch_loss

    for i in range(max(args.warmup, 1)):  # >=1: later loops read `metrics`
        state, metrics = compiled(state, gbs[i % 2], jax.random.key(i))
    _fetch(metrics)

    # tunnel round-trip floor: tiny fresh result each probe, so the timing
    # is dispatch + transfer with negligible compute
    one = jnp.ones((1,), jnp.float32)
    rtts = []
    for i in range(5):
        y = one * float(i + 1)
        t0 = time.perf_counter()
        np.asarray(y)
        rtts.append(time.perf_counter() - t0)
    rtt_ms = statistics.median(rtts) * 1e3

    # --- blocked per-step latency (upper bound; includes one RTT) ---------
    blocked = []
    for i in range(args.steps):
        t0 = time.perf_counter()
        state, metrics = compiled(state, gbs[i % 2], jax.random.key(50 + i))
        _fetch(metrics)
        blocked.append(time.perf_counter() - t0)
    blocked_ms = statistics.median(blocked) * 1e3

    # --- pipelined throughput (async dispatch, one value-sync at the end;
    # the queue is empty here because the blocked loop fetched every step) -
    t0 = time.perf_counter()
    for i in range(args.steps):
        state, metrics = compiled(state, gbs[i % 2], jax.random.key(100 + i))
    _fetch(metrics)
    dt = time.perf_counter() - t0
    pipelined_ms = dt / args.steps * 1e3

    clips_per_sec = B * args.steps / dt
    per_chip = clips_per_sec / n_chips
    # RTT-corrected latency is the fair comparison for the pipelining ratio
    suspect = pipelined_ms < 0.5 * max(blocked_ms - rtt_ms, 1e-6)

    dev = jax.devices()[0]
    # datasheet peak where one exists; a measured matmul-rate calibration
    # on platforms without one (CPU smoke) — labeled, so the MFU stops
    # being null without ever impersonating a silicon fraction
    peak, peak_source = resolve_peak(dev)
    tflops = mfu = None
    if flops_per_step:
        # throughput MFU from the pipelined rate — the deployment-relevant
        # number (the async train loop runs pipelined), and the one with
        # the RTT amortized across the whole window
        tflops = flops_per_step / (pipelined_ms / 1e3) / 1e12 / n_chips
        if peak:
            mfu = tflops / peak
            if mfu > 1.0 and peak_source == "datasheet":
                # >100% of bf16 peak is physically impossible: the
                # platform isn't timing real execution (e.g. a forwarding
                # backend acking the sync early). A measured peak is a
                # proxy ceiling, not physics — exempt from the verdict.
                suspect = True
    log(f"[{name}] {args.steps} steps: blocked {blocked_ms:.1f} ms/step "
        f"(rtt {rtt_ms:.1f}), "
        f"pipelined {pipelined_ms:.1f} ms/step -> {per_chip:.2f} clips/s/chip"
        f"{f', {tflops:.1f} TFLOP/s/chip' if tflops else ''}"
        f"{f', MFU {mfu:.1%}' if mfu else ''}"
        f"{' SUSPECT (device timing not trustworthy)' if suspect else ''}, "
        f"final loss {float(metrics['loss']):.3f}")

    out = {
        "clips_per_sec_per_chip": round(per_chip, 3),
        "step_ms_blocked": round(blocked_ms, 3),
        "step_ms_pipelined": round(pipelined_ms, 3),
        "tunnel_rtt_ms": round(rtt_ms, 3),
        "sync": "value-fetch",  # block_until_ready acks early on axon
        "inputs": "u8" if setup.input_u8 else "f32",
        "compile_s": round(compile_s, 1),
        "batch_per_chip": bsz,
        "frames": frames,
        "crop": crop,
        "suspect": suspect,
        "smoke": bool(args.smoke),
        "platform": dev.platform,
    }
    if flops_per_step:
        out["flops_per_step"] = flops_per_step
        out["tflops_per_sec_per_chip"] = round(tflops, 2)
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
        out["mfu_peak_source"] = peak_source
    return out


# smoke-mode geometry for the trainer lane (frames, crop, per-chip batch);
# module-level so the tier-1 contract test can shrink it further — it checks
# perf-dict plumbing, not CPU conv throughput
SMOKE_TRAINER_SHAPE = (8, 64, 2)


def hbm_headline() -> dict:
    """The memory-ledger triple an obs-armed lane carries (pva-tpu-hbm,
    obs/memory.py): device high-water mark, the fraction of live bytes
    the ledger can attribute to a component, and the provenance label.
    Hosts whose backend exposes no `memory_stats()` (the CPU smoke box)
    report `hbm_source="estimate"` with the peak ATTRIBUTED sum — the
    bench never fakes device bytes. Empty when no ledger is armed."""
    from pytorchvideo_accelerate_tpu.obs import memory as obs_memory

    led = obs_memory.get_ledger()
    if led is None:
        return {}
    return {"hbm_peak_bytes": int(led.peak_bytes()),
            "hbm_attributed_frac": round(led.attributed_frac(), 4),
            "hbm_source": led.source()}


def bench_trainer(args) -> dict:
    """Trainer.fit() on synthetic data — its steady-state clips/s/chip is
    compared (in the parent) against the raw-step number to prove the hot
    loop doesn't sync away the pipelining (VERDICT r2 weak #4)."""
    import jax

    from pytorchvideo_accelerate_tpu.config import (
        CheckpointConfig, DataConfig, GuardConfig, ModelConfig,
        OptimConfig, TrainConfig,
    )
    from pytorchvideo_accelerate_tpu.trainer.loop import Trainer

    frames, crop, bsz = SMOKE_TRAINER_SHAPE if args.smoke else (32, 256, 8)
    n_videos = bsz * len(jax.devices()) * (4 if args.smoke else 16)
    cfg = TrainConfig(
        model=ModelConfig(name="slowfast_r50", num_classes=700),
        data=DataConfig(synthetic=True, synthetic_num_videos=n_videos,
                        num_frames=frames, crop_size=crop, batch_size=bsz,
                        num_workers=2, limit_val_batches=1),
        optim=OptimConfig(num_epochs=2),  # epoch 1 excludes compile
        # flight records / trace rings land under the lane's scratch dir,
        # never the repo root (the default output_dir ".") — a bench
        # round must not churn a generated artifact into the worktree
        checkpoint=CheckpointConfig(output_dir=_scratch_outdir("trainer")),
        # guard ARMED: the lane doubles as the proof that the self-healing
        # machinery (in-graph skip branch + per-step observation) keeps
        # train_recompiles == 0 and reports zero verdicts on a clean run
        guard=GuardConfig(enabled=True),
        mixed_precision="bf16",
    )
    tr = Trainer(cfg)
    res = tr.fit()
    # perf-dict contract: the span-sourced obs keys (obs/ telemetry spine,
    # default-on), the legacy prefetch keys, and the guard verdicts must
    # be present — the smoke run doubles as the CI check that none of the
    # instrumentation silently fell out of fit()
    for key in ("input_wait_frac", "steps_per_sec", "obs_step_s",
                "obs_input_wait_frac", "obs_h2d_s", "train_recompiles",
                "guard_rollbacks", "quarantined_clips"):
        assert key in res, f"fit() perf dict missing {key!r}: {sorted(res)}"
    # steady-state: train-section wall time of the post-compile epoch only
    # (excludes compile, eval, checkpointing — the quantity the raw-step
    # number measures)
    steps_per_epoch = res["steps"] // cfg.optim.num_epochs
    dt = res["epoch_train_times"][-1]
    clips = steps_per_epoch * bsz * len(jax.devices())
    cps_chip = clips / dt / len(jax.devices())
    log(f"[trainer] fit() steady-state epoch: {steps_per_epoch} steps in "
        f"{dt:.2f}s = {cps_chip:.2f} clips/s/chip (incl. data pipeline), "
        f"input_wait_frac {res['input_wait_frac']:.3f}")
    return {"trainer_cps_chip": cps_chip,
            "input_wait_frac": res["input_wait_frac"],
            "obs_step_s": res["obs_step_s"],
            "obs_input_wait_frac": res["obs_input_wait_frac"],
            "obs_h2d_s": res["obs_h2d_s"],
            # steady-state jit-cache growth after warmup (the
            # pva_train_recompiles gauge; analysis/recompile_guard) —
            # anything but 0 means mid-training XLA compile stalls
            "train_recompiles": res["train_recompiles"],
            # self-healing guard verdicts (reliability/guard.py): rollback
            # and quarantine counts — a clean run reports 0 for both
            "guard_rollbacks": res["guard_rollbacks"],
            "quarantined_clips": res["quarantined_clips"],
            "mfu": res.get("mfu"),
            # analytic-counter MFU (analysis/gc_flops.py via fit()):
            # non-null wherever the step traces, including CPU smoke —
            # with the provenance labels the headline must carry
            "mfu_analytic": res.get("mfu_analytic"),
            "mfu_source": res.get("mfu_source"),
            "mfu_peak_source": res.get("mfu_peak_source"),
            # memory-ledger triple (obs/memory.py; the Trainer armed the
            # ledger, so train_state/prefetch-ring bytes are attributed)
            **hbm_headline(),
            "smoke": bool(args.smoke)}


# forced-host slice size for the smoke-mode MULTICHIP lane (the same 8 fake
# CPU devices tier-1 tests mesh semantics on); module-level so tests can
# shrink it
MULTICHIP_FORCED_DEVICES = 8
# bf16 loss-parity tolerance across shard counts (summation-order variance;
# a real sharding bug is orders of magnitude above it — same rationale as
# __graft_entry__'s dryrun)
MULTICHIP_PARITY_RTOL = 2e-2


def _multichip_shape(n: int) -> tuple:
    """(data, model) for the N-device point of the scaling lane: the 2-D
    layout the tentpole exercises — (2,4) at 8 devices."""
    if n >= 8 and n % 4 == 0:
        return (n // 4, 4)
    if n >= 4 and n % 2 == 0:
        return (n // 2, 2)
    return (n, 1)


def bench_multichip(args) -> dict:
    """The MULTICHIP scaling lane: 1 -> N clips/s/chip through the trainer's
    2-D (data, model) GSPMD backbone, with self-verifying numerics.

    Three probes, one honest record:
    - PARITY: the same fixed global batch stepped K times on a 1-device
      mesh and on the N-device (data, model) mesh must produce the same
      per-step loss trajectory (sharding changes the schedule, not the
      math) — `mesh_parity` within MULTICHIP_PARITY_RTOL;
    - SCALING: pipelined clips/s/chip at each mesh point — flat or better
      from 1 -> N is the healthy reading. Forced-host CPU points are
      tagged `forced_host` and are NEVER device numbers;
    - PORTABILITY: a checkpoint written under (1, N) restores under (N, 1)
      and under a single-device mesh at the identical step, and the next
      step's loss matches — the mesh-reshape restore contract
      (docs/PARALLELISM.md runbook).

    Plus one short Trainer.fit() on the N-device mesh so the
    steady-state-zero recompile contract (`train_recompiles == 0`) is
    proven under the 2-D layout, and per-chip MFU rides along whenever the
    XLA flops capture succeeds (whole-program FLOPs / mesh size — model-
    axis shards attributed once, never double-counted).
    """
    import jax
    import numpy as np

    from pytorchvideo_accelerate_tpu.config import (
        CheckpointConfig, DataConfig, MeshConfig, ModelConfig,
        OptimConfig, TrainConfig,
    )
    from pytorchvideo_accelerate_tpu.utils.bench_setup import (
        build_step_setup, fetch_loss, xla_flops,
    )

    devices = jax.devices()
    n = len(devices)
    platform = devices[0].platform
    out: dict = {
        "n_devices": n,
        "platform": platform,
        # smoke mode runs on the forced-host CPU slice by design; a
        # non-smoke lane landing on CPU means the tunnel lied — suspect
        "forced_host": bool(args.smoke),
        "smoke": bool(args.smoke),
        "suspect": platform == "cpu" and not args.smoke,
    }
    data_dim, model_dim = _multichip_shape(n)
    out["mesh_shape"] = [data_dim, model_dim]
    model_name = "tiny3d" if args.smoke else "slowfast_r50"
    frames, crop = (4, 32) if args.smoke else (8, 128)
    # smallest global batch >= 8 every mesh point divides (lcm, not
    # doubling: a 12/24/40-device slice has data_dim = 3/6/10, which no
    # power of two ever divides)
    GB = math.lcm(8, data_dim)
    # smoke (forced-host) runs the lane in fp32: the parity probe is a
    # NUMERICS gate and bf16 summation-order noise compounds across update
    # steps into false divergence. On device the lane stays bf16 (the
    # throughput dtype) and parity compares the FIRST step only — the
    # pre-update forward+loss, where 2e-2 covers reduction-order variance
    # (the dryrun_multichip precedent).
    mp = "fp32" if args.smoke else "bf16"
    out.update(model=model_name, frames=frames, crop=crop, global_batch=GB,
               mixed_precision=mp)
    k_parity = 3
    k_compare = k_parity if mp == "fp32" else 1
    k_timed = args.steps if not args.smoke else 3

    def make_point(devs, mesh_cfg):
        # dropout OFF: with the pinned jax's non-partitionable threefry,
        # in-graph random masks are NOT layout-invariant across mesh
        # shapes, so a parity probe with dropout compares two different
        # (both valid) training runs — the dryrun_multichip convention
        return build_step_setup(
            model_name, frames=frames, crop=crop, batch_per_chip=1,
            num_classes=16, global_batch=GB, devices=list(devs),
            mesh_cfg=mesh_cfg, total_steps=k_parity + k_timed + 4,
            mixed_precision=mp, overrides={"dropout_rate": 0.0},
        )

    def run_point(setup, label):
        """K parity steps (each loss fetched) then a timed pipelined loop."""
        losses = []
        state = setup.state
        gbs = [setup.device_batch(0), setup.device_batch(1)]
        for i in range(k_parity):
            state, metrics = setup.step(state, gbs[i % 2], jax.random.key(i))
            losses.append(fetch_loss(metrics))
        t0 = time.perf_counter()
        for i in range(k_timed):
            state, metrics = setup.step(state, gbs[i % 2],
                                        jax.random.key(100 + i))
        fetch_loss(metrics)
        dt = time.perf_counter() - t0
        cps = GB * k_timed / dt
        log(f"[multichip] {label}: losses {[round(v, 4) for v in losses]}, "
            f"{cps:.2f} clips/s ({cps / setup.n_chips:.2f}/chip)")
        return losses, cps

    # 1-device reference, then the N-device (data, model) point
    ref = make_point(devices[:1], MeshConfig(data=1, model=1))
    ref_losses, ref_cps = run_point(ref, "1-device")
    curve = {"1": round(ref_cps, 3)}
    parity_max_rel = 0.0
    if n > 1:
        big = make_point(devices, MeshConfig(data=data_dim, model=model_dim))
        big_losses, big_cps = run_point(
            big, f"{n}-device ({data_dim},{model_dim})")
        curve[str(n)] = round(big_cps / n, 3)
        parity_max_rel = max(
            abs(a - b) / max(abs(b), 1e-9)
            for a, b in zip(big_losses[:k_compare], ref_losses[:k_compare]))
        flops = None
        try:
            flops = xla_flops(big.step.lower(
                big.state, big.device_batch(0), jax.random.key(0)).compile())
        except Exception as e:
            log(f"[multichip] flops capture failed: {type(e).__name__}: {e}")
        peak, peak_source = resolve_peak(devices[0])
        if flops:
            step_s = GB / big_cps
            tflops_chip = flops / step_s / 1e12 / n
            out["multichip_tflops_per_chip"] = round(tflops_chip, 3)
            if peak:
                out["multichip_mfu"] = round(tflops_chip / peak, 4)
        # analytic counter (analysis/gc_flops.py): the mfu_analytic
        # numerator this lane headlines even where cost-model capture
        # failed — the exact hole that kept mfu null on r03-r05
        try:
            from pytorchvideo_accelerate_tpu.analysis.graphcheck import (
                analytic_step_flops,
            )

            aflops, _ = analytic_step_flops(
                big.step, (big.state, big.device_batch(0),
                           jax.random.key(0)))
            if aflops and peak:
                step_s = GB / big_cps
                out["multichip_mfu_analytic"] = round(
                    aflops / step_s / 1e12 / n / peak, 4)
                out["multichip_mfu_source"] = (
                    "costmodel" if flops else "analytic")
                if peak_source:
                    out["multichip_mfu_peak_source"] = peak_source
        except Exception as e:
            log(f"[multichip] analytic flops failed: "
                f"{type(e).__name__}: {e}")
    out["cps_per_chip"] = curve
    out["parity_max_rel"] = round(parity_max_rel, 6)
    out["mesh_parity"] = bool(parity_max_rel <= MULTICHIP_PARITY_RTOL)

    # checkpoint portability: save under (1, N), restore under (N, 1) and
    # single-chip; the restored state continues with the identical loss
    if n > 1:
        import shutil
        import tempfile

        from pytorchvideo_accelerate_tpu.trainer.checkpoint import Checkpointer

        ckpt_dir = tempfile.mkdtemp(prefix="pva_multichip_ckpt_")
        try:
            a = make_point(devices, MeshConfig(data=1, model=n))
            sa = a.state
            sa, _ = a.step(sa, a.device_batch(0), jax.random.key(0))
            ckpt = Checkpointer(ckpt_dir, use_async=False)
            ckpt.save(1, sa)
            ckpt.wait()
            _, m2 = a.step(sa, a.device_batch(1), jax.random.key(1))
            ref_next = fetch_loss(m2)
            diffs = []
            for tag, devs, mcfg in (
                    (f"({n},1)", devices, MeshConfig(data=n, model=1)),
                    ("single", devices[:1], MeshConfig(data=1, model=1))):
                b = make_point(devs, mcfg)
                sb, _, step_b = ckpt.restore(b.state, step=1, mesh=b.mesh)
                _, mb = b.step(sb, b.device_batch(1), jax.random.key(1))
                next_b = fetch_loss(mb)
                rel = abs(next_b - ref_next) / max(abs(ref_next), 1e-9)
                diffs.append(rel)
                log(f"[multichip] ckpt (1,{n})->{tag}: step {step_b}, "
                    f"next loss {next_b:.5f} vs {ref_next:.5f} "
                    f"(rel {rel:.2e})")
                if step_b != 1:
                    diffs.append(float("inf"))
            ckpt.close()
            out["ckpt_max_rel"] = round(max(diffs), 6)
            out["mesh_ckpt_portable"] = bool(
                max(diffs) <= MULTICHIP_PARITY_RTOL)
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)

    # Trainer.fit() through the N-device 2-D mesh: the recompile contract
    # must hold under the (data, model) layout, not just 1-D DP
    from pytorchvideo_accelerate_tpu.trainer.loop import Trainer

    tcfg = TrainConfig(
        mesh=MeshConfig(data=data_dim, model=model_dim),
        # flight records land under the lane's scratch dir, never "."
        checkpoint=CheckpointConfig(output_dir=_scratch_outdir("multichip")),
        model=ModelConfig(name=model_name, num_classes=16, dropout_rate=0.0),
        data=DataConfig(synthetic=True,
                        synthetic_num_videos=max(4 * data_dim, 8),
                        num_frames=frames, crop_size=crop, batch_size=2,
                        num_workers=1, limit_val_batches=1),
        optim=OptimConfig(num_epochs=1, lr=0.01),
        mixed_precision="bf16",
    )
    # pva-tpu-spmdcheck dynamic half (docs/STATIC_ANALYSIS.md § spmdcheck):
    # record the REAL fit's collective schedule through the hangcheck
    # sections, then replay a deterministic probe segment (real host
    # collectives) under two emulated host labels and diff — run-to-run
    # schedule determinism is the property every pod host must have, so
    # the emulation diffs the real mechanism and the lane headlines
    # spmd_schedule_divergence == 0 forever
    from pytorchvideo_accelerate_tpu.parallel import (
        collectives,
        schedule_recorder as sched_rec,
    )
    from pytorchvideo_accelerate_tpu.parallel.hangcheck import (
        collective_section,
    )

    rec = sched_rec.CollectiveScheduleRecorder(host="fit")
    sched_rec.install_schedule_recorder(rec)
    try:
        res = Trainer(tcfg).fit()
        # non-vacuity: the real fit must have flowed through the watched
        # sections (ckpt_save/ckpt_close at minimum ride every fit)
        out["spmd_fit_sections"] = rec.counts().get("fit", 0)
        for h in range(2):
            with rec.as_host(f"host={h}/2"):
                for i in range(3):
                    with collective_section("step_dispatch", step=i):
                        pass
                    collectives.host_allgather(np.int32(i))
                    collectives.host_broadcast(np.int32(i))
        probe = {k: v for k, v in rec.schedules().items() if k != "fit"}
        div = sched_rec.diff_schedules(probe)
        sched_rec.publish_schedule_report(div)
        out["spmd_schedule_divergence"] = int(div.get(
            "divergence_count", 0))
        # seeded counterpart, every run: one emulated host SKIPS a
        # broadcast — the differ MUST name it, or the clean 0 above is
        # vacuous
        rec.clear()
        for h in range(2):
            with rec.as_host(f"host={h}/2"):
                collectives.host_allgather(np.int32(0))
                if h == 0:
                    collectives.host_broadcast(np.int32(1))
                with collective_section("epoch_sync"):
                    pass
        seeded = sched_rec.diff_schedules(rec.schedules())
        first = seeded.get("first_divergence") or {}
        seeded_ops = {k: (e[1] if e else None)
                      for k, e in (first.get("hosts") or {}).items()}
        out["spmd_divergence_detected"] = bool(
            seeded.get("diverged")
            and "host_broadcast" in seeded_ops.values())
    finally:
        sched_rec.uninstall_schedule_recorder()
    out["train_recompiles"] = res.get("train_recompiles")
    out["trainer_cps_chip"] = round(
        res.get("clips_per_sec", 0.0) / max(n, 1), 3)
    if res.get("mfu") is not None and "multichip_mfu" not in out:
        out["multichip_mfu"] = round(res["mfu"], 4)
    if (res.get("mfu_analytic") is not None
            and "multichip_mfu_analytic" not in out):
        out["multichip_mfu_analytic"] = round(res["mfu_analytic"], 4)
        if res.get("mfu_source"):
            out["multichip_mfu_source"] = res.get("mfu_source")
        if res.get("mfu_peak_source"):
            out["multichip_mfu_peak_source"] = res.get("mfu_peak_source")
    log(f"[multichip] {json.dumps(out)}")
    return out


def bench_data(args) -> dict:
    """Host input-pipeline microbench (SURVEY §7 hard-part 1): encodes a
    small synthetic video tree, then measures raw cv2 decode vs pre-decoded
    cache clips/sec and ClipLoader end-to-end throughput on both transports.

    Non-smoke shapes are the production-bound ones (320x256 source ->
    256^2 crops, the reference transform geometry, run_slowfast_r50.sh):
    these numbers are host-CPU-real — trustworthy on any box, including when
    device timing is not — and they bound the chips/host ratio the input
    pipeline can feed."""
    import shutil
    import tempfile

    import numpy as np

    try:
        import cv2
    except ImportError:
        return {"error": "cv2 unavailable"}

    from pytorchvideo_accelerate_tpu.data.cache import (
        bench_decode_vs_cache, build_cache,
    )
    from pytorchvideo_accelerate_tpu.data.manifest import scan_directory
    from pytorchvideo_accelerate_tpu.data.pipeline import (
        ClipLoader, VideoClipSource,
    )
    from pytorchvideo_accelerate_tpu.data.transforms import make_transform

    tmp = tempfile.mkdtemp(prefix="pva_bench_data_")
    fps = 30.0
    n_videos, n_frames = (4, 24) if args.smoke else (8, 64)
    w_px, h_px = (96, 64) if args.smoke else (320, 256)
    crop = 64 if args.smoke else 256  # reference crop (run_slowfast_r50.sh)
    num_frames = 8
    clip_duration = num_frames * 2 / fps  # sampling_rate 2
    out: dict = {"video_px": f"{w_px}x{h_px}", "crop": crop,
                 "num_videos": n_videos}
    rng = np.random.default_rng(0)
    try:
        root = os.path.join(tmp, "train")
        for c in range(2):
            cls = os.path.join(root, f"class{c}")
            os.makedirs(cls)
            for v in range(n_videos // 2):
                wr = cv2.VideoWriter(
                    os.path.join(cls, f"v{v}.mp4"),
                    cv2.VideoWriter_fourcc(*"mp4v"), fps, (w_px, h_px))
                if not wr.isOpened():
                    return {"error": "mp4v codec unavailable"}
                for _ in range(n_frames):
                    wr.write(rng.integers(0, 255, (h_px, w_px, 3), np.uint8))
                wr.release()

        cache_dir = os.path.join(tmp, "cache")
        t0 = time.perf_counter()
        build_cache(root, cache_dir, fps=fps, short_side=min(h_px, w_px),
                    num_workers=2)
        out["cache_build_s"] = round(time.perf_counter() - t0, 2)
        out.update(bench_decode_vs_cache(
            root, cache_dir, clip_duration=clip_duration,
            n_clips=16 if args.smoke else 48, num_workers=2))

        # loader end-to-end: decode + transforms + batch assembly
        tf = make_transform(num_frames=num_frames, training=True,
                            min_short_side_scale=crop,
                            max_short_side_scale=crop + 64, crop_size=crop)
        manifest = scan_directory(root)
        epochs = 2 if args.smoke else 4
        n_workers = 2 if args.smoke else 4
        out["num_workers"] = n_workers
        def run_loader(key: str, transform, transport: str):
            src = VideoClipSource(manifest, transform, clip_duration,
                                  training=True, seed=0)
            loader = ClipLoader(src, global_batch_size=4, shuffle=True,
                                num_workers=n_workers, transport=transport)
            try:
                clips = 0
                for _ in loader.epoch(0):  # warm pools/caches; drain fully
                    pass                   # so no background decode skews timing
                t0 = time.perf_counter()
                for ep in range(1, epochs + 1):
                    for batch in loader.epoch(ep):
                        clips += batch["label"].shape[0]
                out[key] = round(clips / (time.perf_counter() - t0), 2)
                if loader.transport != transport:  # native lib unavailable
                    out[key + "_note"] = f"fell back to {loader.transport}"
            finally:
                loader.close()

        run_loader("loader_thread_clips_per_sec", tf, "thread")
        run_loader("loader_process_clips_per_sec", tf, "process")
        # u8-through transform (host_cast=u8): quantifies the host-side
        # win of skipping normalize + batching quarter-size clips
        run_loader("loader_thread_u8_clips_per_sec",
                   make_transform(num_frames=num_frames, training=True,
                                  min_short_side_scale=crop,
                                  max_short_side_scale=crop + 64,
                                  crop_size=crop, output_dtype="uint8"),
                   "thread")
        log(f"[data] {out}")
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_serving(args) -> dict:
    """Serving-lane smoke (--serve-smoke): a tiny engine + micro-batcher
    under a threaded synthetic client, measuring what the serving docs tell
    operators to watch — p50/p99 request latency and the batcher fill
    ratio. CPU-real numbers (tiny3d model, parent process is CPU-pinned):
    they prove the queue->bucket->mask->futures machinery and its stats
    plumbing, not chip throughput."""
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import numpy as np

    from pytorchvideo_accelerate_tpu.config import ModelConfig
    from pytorchvideo_accelerate_tpu.models import create_model
    from pytorchvideo_accelerate_tpu.parallel.mesh import make_mesh
    from pytorchvideo_accelerate_tpu.serving import (
        InferenceEngine, MicroBatcher, ServingStats,
    )

    frames, crop, n_requests = (4, 32, 32) if args.smoke else (8, 64, 96)
    num_classes = 16
    mcfg = ModelConfig(name="tiny3d", num_classes=num_classes,
                       dropout_rate=0.0)
    model = create_model(mcfg, "bf16")
    variables = model.init(jax.random.key(0),
                           np.zeros((1, frames, crop, crop, 3), np.float32))
    mesh = make_mesh()
    stats = ServingStats(window=256)
    engine = InferenceEngine(
        model, variables["params"], variables.get("batch_stats", {}), mesh,
        num_classes=num_classes, max_batch_size=8, stats=stats)
    batcher = MicroBatcher(engine, max_wait_ms=2.0, max_queue=512,
                           stats=stats)
    stats.queue_depth_fn = batcher.queue_depth
    rng = np.random.default_rng(0)
    clip = rng.standard_normal((frames, crop, crop, 3)).astype(np.float32)
    try:
        engine.warmup({"video": clip})  # compiles every bucket up front
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=8) as pool:
            futs = [pool.submit(lambda: batcher.submit({"video": clip})
                                .result(timeout=120))
                    for _ in range(n_requests)]
            logits = [f.result(timeout=180) for f in futs]
        dt = time.perf_counter() - t0
        assert all(np.asarray(l).shape == (num_classes,) for l in logits)
    finally:
        batcher.close()
    snap = stats.snapshot()
    out = {
        "serve_p50_ms": snap["p50_ms"],
        "serve_p99_ms": snap["p99_ms"],
        "serve_fill_ratio": snap["batch_fill_ratio"],
        "serve_rps": round(n_requests / dt, 2),
        "serve_batches": snap["batches"],
        "serve_compiled_buckets": snap["compiled_buckets"],
        "n_requests": n_requests,
        "buckets": list(engine.buckets),
        "smoke": bool(args.smoke),
    }
    log(f"[serving] {out}")
    return out


def bench_transport_crossover(args) -> dict:
    """Thread vs process worker pools on a transform-heavy (GIL-bound)
    workload — no video decode, pure numpy per-item work — at >=4 workers
    (VERDICT r3 item 6: find where, if anywhere, the process transport wins
    on this host, and record the machine context the answer depends on)."""
    from pytorchvideo_accelerate_tpu.data.pipeline import (
        ClipLoader, SyntheticClipSource,
    )

    import numpy as np

    n_items = 32 if args.smoke else 96
    frames, size = (8, 112) if args.smoke else (16, 224)
    out: dict = {"cpus": os.cpu_count(), "num_workers": 4,
                 "frames": frames, "size": size}

    def heavy_transform(raw, rng):
        # deliberately GIL-holding numpy work sized like a real augment stack
        v = raw[:frames].astype(np.float32) / 255.0
        for _ in range(6):
            v = np.clip(v * 1.01 + 0.001, -3, 3)
            v = (v - v.mean(axis=(1, 2), keepdims=True)) / (
                v.std(axis=(1, 2), keepdims=True) + 1e-5)
        return {"video": v}

    for transport in ("thread", "process"):
        src = SyntheticClipSource(heavy_transform, num_videos=n_items,
                                  num_classes=5, raw_frames=frames,
                                  raw_size=(size, size), seed=0)
        loader = ClipLoader(src, global_batch_size=4, shuffle=False,
                            num_workers=4, transport=transport)
        try:
            for _ in loader.epoch(0):  # warm, fully drained
                pass
            t0 = time.perf_counter()
            clips = 0
            for ep in (1, 2):
                for batch in loader.epoch(ep):
                    clips += batch["label"].shape[0]
            out[f"{transport}_clips_per_sec"] = round(
                clips / (time.perf_counter() - t0), 2)
            if loader.transport != transport:
                out[f"{transport}_note"] = f"fell back to {loader.transport}"
        finally:
            loader.close()
    t, p = out.get("thread_clips_per_sec"), out.get("process_clips_per_sec")
    # no verdict when the process run silently fell back to threads — a
    # thread-vs-thread comparison would answer the crossover question wrong
    if t and p and "process_note" not in out:
        out["winner"] = "process" if p > t else "thread"
        out["ratio_process_over_thread"] = round(p / t, 3)
    log(f"[transport] {out}")
    return out


# SERVE_FLEET smoke sizing: (replicas, forced CPU devices for the child,
# offered rps, arrival window s, p99 SLO ms, head-sampling rate for the
# lane's distributed traces). Module-level so the contract test can shrink
# it; the SLO is generous for a CPU child that compiles tiny3d buckets
# while serving — the lane proves the fleet machinery, the absolute
# numbers are honest smoke numbers.
FLEET_SMOKE = dict(replicas=2, devices=2, rate_rps=20.0, duration_s=4.0,
                   slo_p99_ms=2500.0, trace_sample=0.5)
FLEET_FULL = dict(replicas=2, devices=0, rate_rps=100.0, duration_s=10.0,
                  slo_p99_ms=500.0, trace_sample=0.1)

# subprocess body for the fleet lane's TRACED process replica: the shared
# stub engine (host-side forward, no model compile) behind the real
# Scheduler + InferenceServer with tracing armed, so a request routed here
# crosses a REAL process boundary (router -> traceparent HTTP hop ->
# replica scheduler -> engine dispatch) and its trace ring lands in
# {outdir}/trace_ring.json on SIGTERM-drain — the multi-process half of
# the merged fleet timeline. One JSON line {{"url": ...}} once bound.
_TRACE_SRV_CODE = """
import json
from pytorchvideo_accelerate_tpu.obs import trace as obstrace
obstrace.configure_tracing(1.0, seed=0, capacity=8192, output_dir={outdir!r})
from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler
from pytorchvideo_accelerate_tpu.serving.server import InferenceServer
from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
from pytorchvideo_accelerate_tpu.serving.stub import StubEngine

engine = StubEngine(forward_s=0.002, num_classes=16)
engine.model_name = "trace-stub"
stats = ServingStats(window=512)
sched = Scheduler(engine, stats=stats, max_queue=256,
                  realtime_deadline_ms=30000.0)
srv = InferenceServer(engine, sched, stats, host="127.0.0.1", port=0,
                      request_timeout_s=30.0)
host, port = srv.address
print(json.dumps({{"url": "http://%s:%d" % (host, port)}}), flush=True)
srv.serve_forever(drain_on_sigterm=True)
"""


def _spawn_traced_replica(outdir: str, startup_timeout_s: float = 120.0):
    """Start the traced stub serving process; returns (Popen, HttpReplica).
    Uses the shared wedge-safe bind-line reader (fleet/pool.py) — a child
    that wedges before binding fails the lane, never hangs it."""
    import atexit
    import shutil

    from pytorchvideo_accelerate_tpu.fleet.pool import (
        HttpReplica,
        read_line_with_deadline,
    )

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-c", _TRACE_SRV_CODE.format(outdir=outdir)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True)

    def reap():
        # a lane failure between spawn and the trace-collection teardown
        # propagates straight out of bench_fleet; the bench child then
        # exits, but this SUBPROCESS would be reparented to init and serve
        # forever — reap it (idempotent: the normal path already waited)
        if proc.poll() is None:
            try:
                proc.kill()
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 - best-effort at interpreter exit
                pass
        shutil.rmtree(outdir, ignore_errors=True)

    atexit.register(reap)
    # match on the URL payload so a stray library line on the child's
    # stdout (a warning, a banner) can't be mistaken for the bind line;
    # ANY failure from here kills the child — it must not idle on its
    # port until the atexit reaper while the lane runs degraded
    try:
        line, eof = read_line_with_deadline(proc, startup_timeout_s,
                                            match='"url"',
                                            name="fleet-trace-read")
        if not (line or "").strip():
            raise RuntimeError(
                f"traced replica "
                f"{'closed stdout' if eof else 'produced no URL'} within "
                f"{startup_timeout_s}s (exit={proc.poll()})")
        url = json.loads(line)["url"]
    except Exception:
        proc.kill()
        raise
    return proc, HttpReplica("trace-proc", url, pid=proc.pid,
                             timeout_s=30.0)


def bench_fleet(args) -> dict:
    """The SERVE_FLEET lane: ≥2 `InferenceEngine` replicas on disjoint
    meshes behind the fleet router, driven OPEN-loop (Poisson arrivals,
    heavy-tail view mix) while a blue/green checkpoint hot-swap lands
    mid-load. Headlines `serve_rps` / `serve_p99_ms_under_load` /
    `swap_blackout_ms` / `fleet_shed_frac`; a non-smoke run that fell back
    to CPU refuses to headline (suspect), per the standing bench rule.

    Proof obligations baked into the record (asserted by --smoke):
    - the open-loop schedule was KEPT (`open_loop_ok`) — otherwise the
      harness degraded to closed-loop and the rps/p99 numbers are fiction;
    - zero failed (non-shed) requests across the whole run, INCLUDING the
      mid-load swap — sheds are policy, failures are bugs;
    - the swap measurably cut over: post-swap logits differ from pre-swap
      logits for the same probe clip (params are scaled on export);
    - distributed tracing (obs/trace.py) is ARMED for the lane: in smoke
      a third, traced stub-engine replica runs as a REAL separate process,
      the lane merges its trace ring with the child's into
      fleet_trace.json (`pva-tpu-trace` machinery), and ≥1 sampled request
      demonstrably spans router → HTTP hop → replica scheduler → engine
      dispatch across the process boundary (`trace_linked`), with the
      tracer's self-measured overhead under 2% of the run
      (`trace_overhead_frac`).
    """
    import shutil
    import tempfile
    import threading

    import jax
    import numpy as np
    import optax

    from pytorchvideo_accelerate_tpu.config import (
        DataConfig, MeshConfig, ModelConfig, TrainConfig,
    )
    from pytorchvideo_accelerate_tpu.fleet import (
        LoadGen, LocalReplica, ReplicaPool, Router, Scheduler,
        heavy_tail_clip_factory, hot_swap,
    )
    from pytorchvideo_accelerate_tpu.models import create_model
    from pytorchvideo_accelerate_tpu.parallel.mesh import make_mesh
    from pytorchvideo_accelerate_tpu.serving import (
        InferenceEngine, ServingStats,
    )
    from pytorchvideo_accelerate_tpu.trainer.checkpoint import (
        export_inference,
    )
    from pytorchvideo_accelerate_tpu.trainer.train_state import TrainState

    from pytorchvideo_accelerate_tpu.obs import trace as obstrace
    from pytorchvideo_accelerate_tpu.obs import tracetool

    shape = FLEET_SMOKE if args.smoke else FLEET_FULL
    frames, crop = (4, 32) if args.smoke else (8, 64)
    num_classes = 16
    devices = jax.devices()
    platform = devices[0].platform
    # tracing ARMED for the whole lane (head-sampled requests + forced
    # probes); the ring merges with the traced process replica's below
    tracer = obstrace.configure_tracing(shape["trace_sample"], seed=0,
                                        capacity=16384)
    # the acceptance bar is >= 2 replicas; on a 1-device host they share
    # the device (distinct engines/executables), on the forced-host slice
    # and real multi-chip they land on disjoint single-device meshes
    n_rep = shape["replicas"]
    cfg = TrainConfig(
        mesh=MeshConfig(data=1),
        model=ModelConfig(name="tiny3d", num_classes=num_classes,
                          dropout_rate=0.0),
        data=DataConfig(num_frames=frames, crop_size=crop),
    )
    model = create_model(cfg.model, "bf16")
    variables = model.init(
        jax.random.key(0),
        np.zeros((1, frames, crop, crop, 3), np.float32))
    params, bstats = variables["params"], variables.get("batch_stats", {})

    rng = np.random.default_rng(0)
    base_clip = {"video": rng.standard_normal(
        (frames, crop, crop, 3)).astype(np.float32)}
    two_view = {"video": np.stack([base_clip["video"]] * 2)}

    replicas = []
    for i in range(n_rep):
        # one device per replica when the slice allows (the forced-host
        # multi-device CI path); engines share weights, not executables
        dev = devices[i % len(devices)]
        mesh = make_mesh(MeshConfig(data=1), devices=[dev])
        stats = ServingStats(window=2048)
        engine = InferenceEngine(model, params, bstats, mesh,
                                 num_classes=num_classes, max_batch_size=4,
                                 stats=stats, model_name="tiny3d")
        log(f"[fleet] replica {i} on {dev}: warming buckets "
            f"{engine.buckets} (1- and 2-view)")
        engine.warmup(base_clip)
        engine.warmup(two_view)
        sched = Scheduler(engine, max_queue=256, stats=stats,
                          realtime_deadline_ms=shape["slo_p99_ms"] * 4,
                          batch_max_wait_ms=5.0, name=f"r{i}")
        replicas.append(LocalReplica(f"r{i}", sched))
    # in smoke, a third replica is a REAL traced serving process (stub
    # engine, no compile): requests routed there cross the traceparent
    # HTTP hop, making the merged trace genuinely multi-process. It joins
    # the pool only AFTER the open-loop window — its JSON serialization
    # would otherwise contend with the arrival thread and slip the
    # schedule (open_loop_ok) the lane exists to keep honest — and the
    # weight-swap probes pin to replicas[0] (a LocalReplica the hot-swap
    # actually cuts over), so the stub cannot contaminate them either.
    trace_proc = None
    trace_dir = None
    trace_replica = None
    if args.smoke:
        trace_dir = tempfile.mkdtemp(prefix="pva_fleet_trace_")
        try:
            trace_proc, trace_replica = _spawn_traced_replica(trace_dir)
            log(f"[fleet] traced process replica at {trace_replica.url} "
                f"(pid {trace_proc.pid})")
        except Exception as e:  # noqa: BLE001 - lane degrades, smoke asserts catch it
            log(f"[fleet] traced process replica failed to start: {e}")
    pool = ReplicaPool(replicas, health_interval_s=0.25)
    router = Router(pool)

    # the green checkpoint: same model, deterministically different weights
    # (scaled), exported through the REAL artifact path so the swap
    # exercises from_artifact -> pre-warm -> cutover end to end
    art_dir = tempfile.mkdtemp(prefix="pva_fleet_swap_")
    green_params = jax.tree.map(lambda x: x * 1.25, params)
    export_inference(
        art_dir, TrainState.create(green_params, bstats, optax.sgd(0.1)),
        config=cfg, meta={"num_classes": num_classes, "model": "tiny3d"})

    pre_logits = np.asarray(
        replicas[0].submit(base_clip).result(timeout=60), np.float32)

    swap_out: dict = {}
    gen = LoadGen(router.submit, rate_rps=shape["rate_rps"],
                  duration_s=shape["duration_s"],
                  clip_factory=heavy_tail_clip_factory(
                      base_clip, view_mix=((1, 0.9), (2, 0.1))),
                  seed=0, priority="realtime")

    def swapper():
        time.sleep(shape["duration_s"] * 0.4)  # mid-load, by construction
        try:
            swap_out.update(hot_swap(replicas, art_dir))
        except Exception as e:  # noqa: BLE001 - a failed swap IS the result
            swap_out["error"] = f"{type(e).__name__}: {e}"

    st = threading.Thread(target=swapper, daemon=True)
    st.start()
    run_wall = None
    try:
        t_run0 = time.perf_counter()
        report = gen.run()
        load_wall = time.perf_counter() - t_run0
        st.join(timeout=300.0)
        # the traced process replica joins the rotation now (post-load):
        # list append is safe against the poller's iteration, and the
        # fresh member is routable immediately (never marked down)
        if trace_replica is not None:
            pool.replicas.append(trace_replica)
        # forced-sample probes (head sampling bypassed — debug traces):
        # the idle router rotates ties round-robin, so 4 probes guarantee
        # every pool member, INCLUDING the traced process replica, serves
        # at least one fully-sampled request
        t_probe0 = time.perf_counter()
        for i in range(4):
            h = tracer.start("trace_probe", force=True, seq=i)
            try:
                with h:
                    router.submit(base_clip).result(timeout=60)
            except Exception as e:  # noqa: BLE001 - probe failure is lane evidence
                log(f"[fleet] trace probe {i} failed: {e}")
        # overhead denominator: the phases that actually carried traced
        # traffic (load window + probe burst) — including the idle
        # swap-join wait would deflate the fraction the smoke gate checks
        run_wall = max(load_wall + time.perf_counter() - t_probe0, 1e-6)
        post_logits = np.asarray(
            replicas[0].submit(base_clip).result(timeout=60), np.float32)
    finally:
        router.close()
        shutil.rmtree(art_dir, ignore_errors=True)
    # --- trace collection: SIGTERM-drain the process replica (its ring
    # dumps to trace_dir/trace_ring.json), merge with this process's ring
    # into one timeline, and verify the cross-process linkage ------------
    trace_out: dict = {}
    try:
        payloads = [tracer.export()]
        if trace_proc is not None:
            try:
                trace_proc.send_signal(signal.SIGTERM)
                trace_proc.wait(timeout=60)
            except Exception:  # noqa: BLE001 - a wedged drain must not hang the lane
                trace_proc.kill()
                trace_proc.wait()
            ring_path = os.path.join(trace_dir, "trace_ring.json")
            try:
                with open(ring_path) as f:
                    payloads.append(json.load(f))
            except (OSError, ValueError) as e:
                log(f"[fleet] traced replica ring unreadable: {e}")
        merged = tracetool.merge_exports(payloads)
        merged_path = os.path.join(HERE, "fleet_trace.json")
        with open(merged_path, "w") as f:
            json.dump(merged, f)
        summary = tracetool.summarize(merged)
        tstats = tracer.stats()
        # ≥1 sampled request spanning router->replica->engine ACROSS the
        # process boundary: a trace with events from >=2 pids that reaches
        # an engine-side device_dispatch
        linked = tracetool.linked_traces(
            merged, require_names=("device_dispatch",), min_pids=2)
        trace_out = {
            "trace_sampled": int(tstats["sampled"]),
            # head-sampled = sampled minus forced debug probes: the number
            # that proves the obs.trace_sample_rate decision stream works
            # (the probes alone would trivially satisfy a >=1 assert)
            "trace_head_sampled": int(tstats["sampled"]
                                      - tstats["forced"]),
            "trace_overhead_frac": round(
                tstats["overhead_s"] / run_wall, 5) if run_wall else None,
            "trace_linked": bool(linked) if args.smoke else None,
            "trace_events": summary["events"],
            "trace_multiprocess": summary["traces_multiprocess"],
            "trace_export": merged_path,
        }
        log(f"[fleet] trace: {summary['events']} events over "
            f"{summary['traces']} traces from pids {summary['pids']}, "
            f"{len(linked)} cross-process linked")
    except Exception as e:  # noqa: BLE001 - trace plumbing must not sink the lane
        log(f"[fleet] trace collection failed: {type(e).__name__}: {e}")
    finally:
        if trace_dir:
            shutil.rmtree(trace_dir, ignore_errors=True)
        obstrace.disable_tracing()
    fleet_snap = router.fleet_snapshot()
    swapped = not np.allclose(pre_logits, post_logits, atol=1e-6)
    out = {
        "serve_rps": report["achieved_rps"],
        "serve_p99_ms_under_load": report["p99_ms"],
        "swap_blackout_ms": swap_out.get("swap_blackout_ms"),
        "fleet_shed_frac": report["shed_frac"],
        "fleet_failed": int(report["failed"]),
        "offered_rps": report["offered_rps"],
        "open_loop_ok": report["open_loop_ok"],
        "weights_cut_over": bool(swapped),
        "replicas": n_rep,
        "slo_p99_ms": shape["slo_p99_ms"],
        "fleet_requests": fleet_snap["requests"],
        "router_retries": fleet_snap["router_retries"],
        "swap": {k: v for k, v in swap_out.items()},
        "platform": platform,
        "smoke": bool(args.smoke),
        # a non-smoke fleet lane on CPU is a lying tunnel, not a serving
        # measurement — refuse to headline (finalize drops the perf keys)
        "suspect": platform == "cpu" and not args.smoke,
    }
    out.update(trace_out)
    if "error" in swap_out:
        out["error"] = f"hot-swap failed: {swap_out['error']}"
    log(f"[fleet] {json.dumps(out)}")
    return out


# FLEET_AUTO sizing: the control-loop lane runs entirely in-process on
# stub engines (the controllers are host-side control code; the subprocess
# spawn actuator is chaos leg `autoscale_kill`'s job), so smoke and full
# differ only in traffic volume and SLO tightness. Module-level so the
# contract test can shrink it. The x3d stub serves buckets (1, 2) at
# `forward_s` per launch, capping one replica near 2/forward_s rps — the
# step rate is sized to genuinely overload the single starting replica.
FLEET_AUTO_SMOKE = dict(base_rps=6.0, step_rps=60.0, base_s=1.0,
                        step_s=3.0, forward_s=0.05, probe_s=1.5,
                        slo_p99_ms=2500.0, converge_deadline_s=8.0,
                        sessions=4, advances=6, budget_mb=3000.0,
                        canary_rps=30.0, canary_burst_s=1.2)
FLEET_AUTO_FULL = dict(base_rps=10.0, step_rps=120.0, base_s=2.0,
                       step_s=6.0, forward_s=0.05, probe_s=3.0,
                       slo_p99_ms=1000.0, converge_deadline_s=15.0,
                       sessions=8, advances=8, budget_mb=3000.0,
                       canary_rps=60.0, canary_burst_s=2.5)


def bench_fleet_auto(args) -> dict:
    """The FLEET_AUTO lane: the fleet-intelligence control loops
    (fleet/control/, docs/SERVING.md § fleet intelligence) closed-loop
    against real traffic. Headlines `autoscale_converge_s` /
    `fleet_scaledown_shed_frac` / `canary_rollback` /
    `fleet_models_served`; the verdict keys (`canary_promoted`,
    `fleet_session_failures`) ride even on a refused round.

    Proof obligations baked into the record (asserted by --smoke):
    - CONVERGENCE: an open-loop traffic STEP (loadgen piecewise profile)
      overloads the starting fleet; the damped autoscaler grows it, the
      last scaling action lands within `converge_deadline_s` of the step,
      and a steady-state probe at the FULL stepped rate then holds the
      p99 SLO with zero non-shed failures at the size the controller
      chose — the step run's own p99 includes the pre-scale backlog by
      construction and is recorded, never asserted;
    - SCALE-DOWN SAFETY: draining a victim re-homes every live streaming
      session (affinity dropped -> deterministic re-establish from the
      client's resendable window on a survivor); every advance across
      the drain verifies `stub_stream_logits` equality against the
      client's own window, zero non-shed failures, and the controller
      never drains the last routable replica;
    - MULTI-MODEL: >=2 model families (x3d_s + videomae_t) serve off ONE
      pool under a shared `ModelBudget`; pushing a third family past the
      budget sheds THAT family at the fleet door while the in-budget
      families keep serving untouched;
    - CANARY: a seeded-regression artifact (12x slower by construction)
      is auto-rolled-back by the escalation ladder with direction-aware
      perfdiff evidence, the blue engines restored; an equal-cost clean
      artifact under the SAME controller knobs evaluates clean and is
      promoted fleet-wide.
    """
    import jax
    import numpy as np

    from pytorchvideo_accelerate_tpu.fleet.control import (
        Autoscaler,
        CanaryController,
        ModelBudget,
        MultiModelFleet,
    )
    from pytorchvideo_accelerate_tpu.fleet.loadgen import (
        LoadGen,
        step_profile,
    )
    from pytorchvideo_accelerate_tpu.fleet.pool import (
        LocalReplica,
        ReplicaPool,
    )
    from pytorchvideo_accelerate_tpu.fleet.router import Router
    from pytorchvideo_accelerate_tpu.fleet.scheduler import Scheduler
    from pytorchvideo_accelerate_tpu.serving.batcher import QueueFullError
    from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
    from pytorchvideo_accelerate_tpu.serving.stub import (
        StubEngine,
        StubStreamEngine,
        stub_stream_logits,
    )

    from pytorchvideo_accelerate_tpu.obs import memory as obs_memory

    shape = FLEET_AUTO_SMOKE if args.smoke else FLEET_AUTO_FULL
    platform = jax.devices()[0].platform
    fwd = shape["forward_s"]
    # arm the memory ledger for the lane's hbm_* triple (stub engines pin
    # no device arrays, so the attribution is trivially honest here —
    # backend peak where measured, zero-attributed estimate elsewhere)
    obs_memory.configure()

    def mk_replica(name, model, engine):
        stats = ServingStats(window=1024)
        # deadline effectively off: convergence must be driven by the
        # controller's queue/p99 signals, not masked by deadline sheds
        sched = Scheduler(engine, stats=stats, max_queue=512,
                          realtime_deadline_ms=30000.0,
                          name=f"auto-{name}")
        return LocalReplica(name, sched, model=model)

    def mk_x3d(name, tag=0.0, forward_s=None):
        return mk_replica(name, "x3d_s",
                          StubEngine(tag=tag, buckets=(1, 2),
                                     forward_s=(fwd if forward_s is None
                                                else forward_s)))

    # one pool, two families: a single x3d_s request replica (the one the
    # traffic step overloads) + two videomae_t stream replicas
    replicas = [mk_x3d("x3d-0"),
                mk_replica("vm-0", "videomae_t",
                           StubStreamEngine(forward_s=0.002)),
                mk_replica("vm-1", "videomae_t",
                           StubStreamEngine(forward_s=0.002))]
    pool = ReplicaPool(replicas, health_interval_s=0.1, name="auto")
    router = Router(pool)
    budget = ModelBudget(shape["budget_mb"])
    mmf = MultiModelFleet(router, budget)
    mmf.register_model("x3d_s", 1200.0,
                       latency_buckets_ms=(50, 100, 250, 1000, 2500))
    mmf.register_model("videomae_t", 1400.0,
                       latency_buckets_ms=(100, 500, 2000))
    base = {"video": np.zeros((2, 4, 4, 3), np.float32)}

    def x3d_submit(clip, **kw):
        return mmf.submit(clip, model="x3d_s", **kw)

    spawn_n = [0]

    def spawn():
        spawn_n[0] += 1
        return mk_x3d(f"x3d-auto-{spawn_n[0]}")

    out: dict = {}
    try:
        # --- phase A: convergence under an open-loop traffic step -------
        asc = Autoscaler(router, spawn_fn=spawn,
                         min_replicas=len(replicas),
                         max_replicas=len(replicas) + 4,
                         slo_p99_ms=shape["slo_p99_ms"],
                         queue_high=3.0, queue_low=0.3,
                         downscale_frac=0.1, cooldown_s=0.4,
                         interval_s=0.08, ewma_alpha=0.6,
                         drain_grace_s=2.0)
        replicas_start = len(pool.routable())
        asc.start()
        t0 = time.monotonic()
        step_report = LoadGen(
            x3d_submit,
            profile=step_profile((shape["base_s"], shape["base_rps"]),
                                 (shape["step_s"], shape["step_rps"])),
            clip_factory=lambda rng: dict(base), seed=0).run()
        t_step = t0 + shape["base_s"]
        post = [e for e in asc.actions_since(t_step)
                if e["action"] in ("up", "down", "replace")]
        converge_s = (round(max(e["t"] for e in post) - t_step, 3)
                      if post else 0.0)
        asc.close()
        scaled_to = len(pool.routable())
        probe = LoadGen(x3d_submit, rate_rps=shape["step_rps"],
                        duration_s=shape["probe_s"],
                        clip_factory=lambda rng: dict(base), seed=1).run()
        converged = bool(post) and scaled_to > replicas_start \
            and probe["p99_ms"] <= shape["slo_p99_ms"] \
            and probe["failed"] == 0 \
            and converge_s <= shape["converge_deadline_s"]
        log(f"[fleet_auto] converge: {replicas_start}->{scaled_to} "
            f"replicas in {converge_s}s, steady p99 {probe['p99_ms']} ms "
            f"(SLO {shape['slo_p99_ms']})")

        # --- multi-model budget: the third family sheds, the pool serves
        models_served = len(mmf.models())
        mmf.register_model("mvit_b", shape["budget_mb"])  # guaranteed over
        budget_shed = False
        try:
            mmf.submit(dict(base), model="mvit_b")
        except QueueFullError:
            budget_shed = True
        in_budget_ok = True
        try:
            mmf.submit(dict(base), model="x3d_s").result(timeout=30)
        except Exception:  # noqa: BLE001 - any failure breaks the claim
            in_budget_ok = False

        # --- phase B: scale-down re-homes every live streaming session -
        window, stride, fshape = 8, 2, (4, 4, 3)
        rng = np.random.default_rng(7)
        windows: dict = {}
        counts = {"advances": 0, "shed": 0, "failed": 0}

        def advance(sid, k, end):
            frames = rng.standard_normal(
                (stride,) + fshape).astype(np.float32)
            if k == 0:
                windows[sid] = rng.standard_normal(
                    (window,) + fshape).astype(np.float32)
            windows[sid] = np.concatenate(
                [windows[sid][stride:], frames], 0)
            counts["advances"] += 1
            try:
                # window attached on every advance (the resendable-window
                # client contract): a re-homed session re-establishes on
                # the survivor transparently, and the logits stay a pure
                # function of the client's own window — checkable
                res = mmf.submit(
                    {"video": frames}, model="videomae_t",
                    session={"sid": sid, "stride": stride, "end": end,
                             "window": windows[sid]}).result(timeout=30)
            except QueueFullError:
                counts["shed"] += 1
                return
            except Exception:  # noqa: BLE001 - any other failure is a bug
                counts["failed"] += 1
                return
            want = stub_stream_logits(windows[sid], 4)
            if not np.allclose(np.asarray(res).ravel(), want.ravel(),
                               atol=1e-5):
                counts["failed"] += 1

        n_sessions = int(shape["sessions"])
        for i in range(n_sessions):
            advance(f"fa-{i}", 0, False)
        # both stream replicas must hold >=1 pinned session before the
        # drain (the re-home target must outlive the victim); affinity
        # ties round-robin, so a few extra establishes always balance it
        for _ in range(8):
            if all(router.sessions_on(r.name) for r in pool.routable()
                   if getattr(r, "model", None) == "videomae_t"):
                break
            advance(f"fa-{n_sessions}", 0, False)
            n_sessions += 1
        for i in range(n_sessions):
            advance(f"fa-{i}", 1, False)
        # a second controller parameterized for the drain leg: idle is
        # queue-driven (the SLO term effectively off), so with traffic
        # gone it steps the target down once per cooldown; victims are
        # fewest-sessions-first, so the spawned x3d replicas reap first
        # and the first session-carrying victim proves the re-home
        asc2 = Autoscaler(router, spawn_fn=spawn, min_replicas=1,
                          max_replicas=len(pool.replicas) + 1,
                          slo_p99_ms=1e9, queue_high=3.0, queue_low=0.3,
                          downscale_frac=0.5, cooldown_s=0.05,
                          interval_s=0.05, ewma_alpha=1.0,
                          drain_grace_s=2.0)
        rehomed = 0
        for _ in range(64):
            before = {r.name: router.sessions_on(r.name)
                      for r in pool.routable()}
            if asc2.step() == "down":
                names = {r.name for r in pool.replicas}
                rehomed += sum(len(sids) for n, sids in before.items()
                               if n not in names)
            if rehomed or len(pool.routable()) <= 1:
                break
            time.sleep(0.06)
        asc2.close()
        for k in range(2, int(shape["advances"])):
            for i in range(n_sessions):
                advance(f"fa-{i}", k, k == int(shape["advances"]) - 1)
        shed_frac = (round(counts["shed"] / counts["advances"], 4)
                     if counts["advances"] else 0.0)
        log(f"[fleet_auto] scale-down: {rehomed} session(s) re-homed, "
            f"{counts['failed']} failure(s), shed_frac {shed_frac} over "
            f"{counts['advances']} advances")
    finally:
        router.close()

    # --- phase C: canary rollout — seeded regression, then a clean one -
    creps = [mk_x3d(f"cn-{i}", forward_s=0.004) for i in range(4)]
    pool2 = ReplicaPool(creps, health_interval_s=0.2, name="canary")
    router2 = Router(pool2)
    try:
        def burst(seed):
            return LoadGen(router2.submit, rate_rps=shape["canary_rps"],
                           duration_s=shape["canary_burst_s"],
                           clip_factory=lambda rng: dict(base),
                           seed=seed).run()

        cc = CanaryController(router2, fraction=0.25, threshold=0.5,
                              rollback_after=2)
        cc.start_rollout(lambda r: StubEngine(tag=7.0, forward_s=0.05,
                                              buckets=(1, 2)),
                         label="seeded-regression")
        verdict: dict = {}
        rollbacks = 0
        for i in range(cc.rollback_after):
            burst(10 + i)
            verdict = cc.evaluate()
            if verdict.get("rolled_back"):
                rollbacks += 1
                break
        restored = all(r.scheduler.current_engine().tag == 0.0
                       for r in creps)
        cc2 = CanaryController(router2, fraction=0.25, threshold=0.5,
                               rollback_after=2)
        cc2.start_rollout(lambda r: StubEngine(tag=5.0, forward_s=0.004,
                                               buckets=(1, 2)),
                          label="clean")
        burst(20)
        clean = cc2.evaluate()
        promoted = False
        if clean["action"] == "observe" and clean["strikes"] == 0:
            cc2.promote()
            promoted = all(r.scheduler.current_engine().tag == 5.0
                           for r in creps)
        log(f"[fleet_auto] canary: seeded regressions "
            f"{verdict.get('regressions')} -> {rollbacks} rollback(s); "
            f"clean -> promoted={promoted}")
    finally:
        router2.close()

    # the lane's own hbm triple, read BEFORE phase D swaps the process
    # ledger for its fake-stats probe (a probe's injected backend must
    # never color the lane's provenance label)
    hbm = hbm_headline()

    # --- phase D: burn-rate alert discipline + the budget-lies probe ---
    # D1: a seeded SLO breach must fire its multi-window burn-rate rule
    # EXACTLY once and clear on recovery (obs/alerts.py hysteresis) —
    # zero fires during the calm phases is the false-positive gate
    # scripts/analyze.sh reads off this record. Synthetic clock: the
    # windows are seconds-denominated, the probe must not be wall-paced.
    from pytorchvideo_accelerate_tpu.obs.alerts import AlertEngine, AlertRule
    from pytorchvideo_accelerate_tpu.obs.history import MetricsHistory
    from pytorchvideo_accelerate_tpu.obs.registry import Registry

    areg = Registry()
    g_p99 = areg.gauge("pva_probe_p99_ms",
                       "seeded SLO-breach driver (bench fleet_auto)")
    eng = AlertEngine(
        MetricsHistory(registry=areg, capacity=128),
        [AlertRule(name="p99_burn", kind="gauge", key="pva_probe_p99_ms",
                   objective=float(shape["slo_p99_ms"]),
                   fast_s=2.0, slow_s=8.0, hold_clear=2)],
        registry=areg)
    slo = float(shape["slo_p99_ms"])
    t_sim, fires = 1000.0, []
    for factor, ticks in ((0.25, 20), (4.0, 12), (0.25, 20)):
        g_p99.set(factor * slo)
        for _ in range(ticks):
            eng.tick(now=t_sim)
            t_sim += 1.0
        fires.append(eng.fires("p99_burn"))
    alert_fired_once = fires[0] == 0 and fires[1] == 1
    alert_cleared = not eng.active()
    # fires outside the seeded excursion: calm-phase fires + flap re-fires
    alert_false_positives = fires[0] + (fires[2] - fires[1])

    # D2: the budget-lies probe — a family that under-declares its
    # footprint must be refused where the ledger can measure it. Injected
    # backend stats flip ModelBudget onto its measured path; the liar
    # declares 10 MB (fits), the ledger sees the 90 MB weight pin it
    # actually made (sheds). Declared-vs-measured admission flipping on
    # the same state IS the acceptance criterion (ISSUE 18).
    obs_memory.configure(stats_fn=lambda: {
        "bytes_in_use": 200 * 10**6, "peak_bytes_in_use": 220 * 10**6,
        "bytes_limit": 10**9})
    lies = ModelBudget(100.0)
    lies.register("honest", 60.0)
    lies.register("liar", 10.0)
    admitted_declared = "liar" not in lies.over_budget()
    obs_memory.register("model_weights:liar", 90 * 10**6,
                        declared=10 * 10**6)
    refused_measured = "liar" in lies.over_budget()
    led = obs_memory.get_ledger()
    liar_drift = round(led.drift().get("model_weights:liar", 0.0), 2)
    # disarm: the fake stats_fn must not outlive the probe
    obs_memory.configure(enabled=False)
    budget_lies_refused = bool(admitted_declared and refused_measured)
    log(f"[fleet_auto] alerts: fires per phase {fires} "
        f"(cleared={alert_cleared}); budget-lies refused="
        f"{budget_lies_refused} (liar drift {liar_drift})")

    out = {
        "autoscale_converge_s": converge_s,
        "fleet_scaledown_shed_frac": shed_frac,
        "canary_rollback": rollbacks,
        "fleet_models_served": models_served,
        "canary_promoted": bool(promoted),
        "fleet_session_failures": int(counts["failed"]),
        "fleet_sessions_rehomed": int(rehomed),
        "autoscale_converged": bool(converged),
        "converge_deadline_s": shape["converge_deadline_s"],
        "replicas_start": replicas_start,
        "scaled_up_to": scaled_to,
        "steady_p99_ms": probe["p99_ms"],
        "steady_failed": int(probe["failed"]),
        "step_p99_ms": step_report["p99_ms"],
        "step_shed_frac": step_report["shed_frac"],
        "open_loop_ok": bool(step_report["open_loop_ok"]
                             and probe["open_loop_ok"]),
        "slo_p99_ms": shape["slo_p99_ms"],
        "budget_shed_ok": bool(budget_shed and in_budget_ok),
        # phase D verdicts (pva-tpu-hbm): burn-rate alert discipline —
        # the seeded breach fired once and cleared, zero calm-phase or
        # flap fires — and the measured-byte admission flip
        "alert_false_positives": int(alert_false_positives),
        "alert_fired_once": bool(alert_fired_once),
        "alert_cleared": bool(alert_cleared),
        "budget_lies_refused": budget_lies_refused,
        "budget_liar_drift": liar_drift,
        **hbm,
        "canary_regressions": sorted(verdict.get("regressions", [])),
        "canary_strikes": verdict.get("strikes"),
        "canary_blue_restored": bool(restored),
        "sessions": n_sessions,
        "advances": counts["advances"],
        "platform": platform,
        "smoke": bool(args.smoke),
        # the standing bench rule: a non-smoke control lane on CPU is a
        # lying tunnel, not a fleet measurement — refuse to headline
        "suspect": platform == "cpu" and not args.smoke,
    }
    log(f"[fleet_auto] {json.dumps(out)}")
    return out


# forced-host slice for the smoke-mode PIPELINE lane (same 8 fake CPU
# devices as the multichip lane); module-level so tests can shrink it
PIPELINE_FORCED_DEVICES = 8
# fp32 loss-parity tolerance across pipeline layouts (the lane runs fp32
# by construction — the parity probe is the acceptance gate, and bf16
# summation-order noise would compound across update steps)
PIPELINE_PARITY_RTOL = 2e-2


def bench_pipeline(args) -> dict:
    """The PIPELINE lane (parallel/pipeline.py; docs/PARALLELISM.md §
    pipeline): pipeline-parallel VideoMAE pretrain on the 2-D (data,
    model) train mesh, with self-verifying numerics.

    Probes, one honest record:
    - PARITY (the acceptance gate): the same fixed global batch stepped K
      times unpipelined (P=1) and through P=2 / P=4 stage pipelines at
      fp32 must produce the same per-step loss trajectory — the stage
      schedule changes WHEN each microbatch's blocks run, never the math;
    - BUBBLE: the analytic fill/drain fraction (P-1)/(M+P-1) next to a
      MEASURED one from a two-point (M, 2M) timing fit at fixed
      microbatch size — t_tick = (T(2M) - T(M)) / M, bubble =
      (P-1)*t_tick / T(M) — because a single run cannot separate
      fill/drain idle from per-tick compute;
    - THROUGHPUT: pipelined clips/s/chip at the P-stage point
      (`pipeline_cps_per_chip`, perfdiff HIGHER_BETTER);
    - DONATION: graphcheck's donation pass over the pipelined step —
      declared donations must alias through the stage shard_map + scan;
    - plus one short Trainer.fit() under the pipelined layout so the
      steady-state-zero recompile contract holds there too
      (`train_recompiles == 0`), with the pipeline perf keys present.

    Smoke runs the whole lane on the forced-host CPU slice (honest
    parity, never device numbers — the multichip convention)."""
    import jax

    from pytorchvideo_accelerate_tpu.config import (
        CheckpointConfig, DataConfig, MeshConfig, ModelConfig,
        OptimConfig, ParallelConfig, TrainConfig,
    )
    from pytorchvideo_accelerate_tpu.parallel.pipeline import (
        analytic_bubble_frac,
    )
    from pytorchvideo_accelerate_tpu.utils.bench_setup import (
        build_step_setup, fetch_loss,
    )

    devices = jax.devices()
    n = len(devices)
    platform = devices[0].platform
    out: dict = {
        "n_devices": n,
        "platform": platform,
        "forced_host": bool(args.smoke),
        "smoke": bool(args.smoke),
        "suspect": platform == "cpu" and not args.smoke,
    }
    if n < 2:
        out["error"] = f"pipeline lane needs >= 2 devices, have {n}"
        return out
    model_name = "videomae_t_pretrain"
    frames, crop = (4, 32) if args.smoke else (16, 224)
    # stage counts this slice supports: P must divide the trunk depth (4)
    # AND the device count must split as (data, P)
    stage_points = [p for p in (2, 4) if n % p == 0 and n // p >= 1]
    if not stage_points:
        # an odd slice (3/5/7 devices) fits no (data, P) split: refuse
        # loudly rather than report a vacuously-true parity verdict for
        # a sweep that never ran
        out["error"] = (f"pipeline lane needs a device count divisible "
                        f"by 2 or 4 for its (data, P) points, have {n}")
        return out
    # every layout must divide the SAME global batch: P=1 needs its n data
    # shards, each P-stage point needs data_shards x microbatches
    # = (n/p) x 2p = 2n — one fixed batch for the whole parity sweep
    GB = math.lcm(n, *(2 * p * (n // p) for p in stage_points))
    k_parity = 3
    k_timed = args.steps if not args.smoke else 3
    out.update(model=model_name, frames=frames, crop=crop, global_batch=GB,
               mixed_precision="fp32", stage_points=stage_points)

    def make_point(stages: int, micro: int = 0):
        mesh_cfg = (MeshConfig(data=n // stages, model=stages)
                    if stages > 1 else MeshConfig(data=n, model=1))
        return build_step_setup(
            model_name, frames=frames, crop=crop, batch_per_chip=1,
            num_classes=16, global_batch=GB, devices=list(devices),
            mesh_cfg=mesh_cfg, total_steps=k_parity + k_timed + 4,
            mixed_precision="fp32", overrides={"dropout_rate": 0.0},
            pipeline_stages=stages, pipeline_microbatches=micro,
        )

    def run_point(setup, label, timed=True):
        losses = []
        state = setup.state
        gbs = [setup.device_batch(0), setup.device_batch(1)]
        for i in range(k_parity):
            state, metrics = setup.step(state, gbs[i % 2], jax.random.key(i))
            losses.append(fetch_loss(metrics))
        cps = dt = None
        if timed:
            t0 = time.perf_counter()
            for i in range(k_timed):
                state, metrics = setup.step(state, gbs[i % 2],
                                            jax.random.key(100 + i))
            fetch_loss(metrics)
            dt = time.perf_counter() - t0
            cps = GB * k_timed / dt
        log(f"[pipeline] {label}: losses {[round(v, 4) for v in losses]}"
            + (f", {cps:.2f} clips/s ({cps / n:.2f}/chip)" if cps else ""))
        return losses, cps, dt

    ref_losses, ref_cps, _ = run_point(make_point(1), "P=1 baseline")
    parity_max_rel = 0.0
    cps_points = {"1": round(ref_cps / n, 3)}
    top_p = stage_points[-1] if stage_points else 1
    for p in stage_points:
        m = 2 * p  # fixed default schedule for the parity points
        setup = make_point(p, m)
        losses, cps, dt_m = run_point(setup, f"P={p} M={m}")
        cps_points[str(p)] = round(cps / n, 3)
        parity_max_rel = max(parity_max_rel, max(
            abs(a - b) / max(abs(b), 1e-9)
            for a, b in zip(losses, ref_losses)))
        if p == top_p:
            out["pipeline_cps_per_chip"] = round(cps / n, 3)
            out["pipeline_stages"] = p
            out["pipeline_microbatches"] = m
            out["pipeline_bubble_frac_analytic"] = round(
                analytic_bubble_frac(p, m), 4)
            # two-point (M, 2M) fit at FIXED microbatch size: double the
            # global batch with the microbatch count so each tick does
            # identical work, then the timing difference isolates t_tick
            setup2 = build_step_setup(
                model_name, frames=frames, crop=crop, batch_per_chip=1,
                num_classes=16, global_batch=2 * GB, devices=list(devices),
                mesh_cfg=MeshConfig(data=n // p, model=p),
                total_steps=k_timed + 4, mixed_precision="fp32",
                overrides={"dropout_rate": 0.0},
                pipeline_stages=p, pipeline_microbatches=2 * m,
            )
            _, _, dt_2m = run_point(setup2, f"P={p} M={2 * m} (fit point)")
            t_m, t_2m = dt_m / k_timed, dt_2m / k_timed
            t_tick = max((t_2m - t_m) / m, 0.0)
            measured = ((p - 1) * t_tick / t_m) if t_m > 0 else None
            out["pipeline_bubble_frac"] = (round(min(measured, 1.0), 4)
                                           if measured is not None else None)
            log(f"[pipeline] P={p}: bubble analytic "
                f"{out['pipeline_bubble_frac_analytic']} measured "
                f"{out['pipeline_bubble_frac']} "
                f"(t_tick {t_tick * 1e3:.1f} ms)")
            # donation through the stage scan, verified on the REAL lane
            # step (the parent's graphcheck gate runs single-device and
            # skips the pipelined target; this child has the mesh)
            try:
                from pytorchvideo_accelerate_tpu.analysis.gc_donation import (
                    check_donation,
                )

                gb0 = setup.device_batch(0)
                findings, summary = check_donation(
                    setup.step, (setup.state, gb0, jax.random.key(0)))
                out["pipeline_donation_verified"] = (
                    summary.get("declared_unaliased") == 0
                    and summary.get("undeclared_donatable") == 0
                    and summary.get("aliased", 0) > 0)
                log(f"[pipeline] donation: {summary}")
            except Exception as e:  # noqa: BLE001 - verdict, not a crash
                log(f"[pipeline] donation check failed: "
                    f"{type(e).__name__}: {e}")
                out["pipeline_donation_verified"] = None
    out["cps_per_chip_by_stages"] = cps_points
    out["parity_max_rel"] = round(parity_max_rel, 6)
    out["pipeline_parity"] = bool(parity_max_rel <= PIPELINE_PARITY_RTOL)

    # Trainer.fit() under the pipelined layout: the recompile contract and
    # the pipeline perf keys must hold end to end, guard composition incl.
    if stage_points:
        p = stage_points[0]
        tcfg = TrainConfig(
            mesh=MeshConfig(data=n // p, model=p),
            # flight records land under the lane's scratch dir, never "."
            checkpoint=CheckpointConfig(
                output_dir=_scratch_outdir("pipeline")),
            parallel=ParallelConfig(pipeline_stages=p),
            model=ModelConfig(name=model_name, num_classes=16,
                              dropout_rate=0.0),
            data=DataConfig(synthetic=True,
                            synthetic_num_videos=max(2 * (n // p) * p, 8),
                            num_frames=frames, crop_size=crop,
                            batch_size=GB // (n // p), num_workers=1,
                            limit_val_batches=1),
            optim=OptimConfig(num_epochs=1, lr=0.01),
            mixed_precision="fp32",
        )
        from pytorchvideo_accelerate_tpu.trainer.loop import Trainer

        res = Trainer(tcfg).fit()
        out["train_recompiles"] = res.get("train_recompiles")
        out["trainer_bubble_frac_analytic"] = res.get(
            "pipeline_bubble_frac_analytic")
        out["trainer_pipeline_cps_per_chip"] = res.get(
            "pipeline_cps_per_chip")
    log(f"[pipeline] {json.dumps(out)}")
    return out


# --- parent orchestration ---------------------------------------------------

def bench_kbench(args) -> dict:
    """Kernel-microbench lane (`pva-tpu-kbench`, ops/kbench.py): each
    fused Pallas/folded kernel vs its XLA reference at the real
    slowfast/x3d hot-path shapes. Speedups are SAME-BACKEND ratios —
    honest on any host — but only a TPU run is a device claim; the
    record carries platform/device labels and raw ms stay here in
    bench_partial.json, never on the headline (the standing
    no-CPU-numbers-as-device-numbers rule)."""
    import jax

    from pytorchvideo_accelerate_tpu.ops.kbench import run_kbench

    res = run_kbench(smoke=args.smoke, log=log)
    res["n_chips"] = len(jax.devices())
    return res


# STREAM lane shapes: `cam` is the simulated camera resolution the client
# decodes at (frames are resized to `crop` for the model — real stream
# clients decode at source resolution); stride <= window/4 per the
# acceptance bar, so the per-advance H2D payload is <= 1/4 of a full
# window by construction
STREAM_SMOKE = dict(window=16, stride=2, crop=32, cam=96, sessions=4,
                    rounds=10, warmup=3, lg_rate_sps=3.0, lg_duration_s=3.0,
                    slo_label_p99_ms=2000.0,
                    trunk_window=32, trunk_crop=64, trunk_rounds=6,
                    trunk_warmup=2, trunk_eval=32)
STREAM_FULL = dict(window=16, stride=2, crop=64, cam=160, sessions=8,
                   rounds=40, warmup=5, lg_rate_sps=8.0, lg_duration_s=8.0,
                   slo_label_p99_ms=1000.0,
                   trunk_window=32, trunk_crop=64, trunk_rounds=20,
                   trunk_warmup=3, trunk_eval=64)
# incremental-vs-full parity tolerance: the two paths run the same ops on
# the same values through DIFFERENT executables, so fp32 fusion-order
# noise is the only allowed difference
STREAM_PARITY_TOL = 2e-4
# trunk-reuse quality gate (docs/SERVING.md § trunk-reuse): the banded
# trunk changes the math, so its speedup may only headline with an
# evaluate() top-1 accuracy delta vs the bidirectional baseline under
# this bound on the lane's fixed-seed synthetic eval — past it, the lane
# refuses the speedup (stream_trunk_refused) and headlines the delta
STREAM_TRUNK_TOP1_TOL = 0.15


def _write_stream_fixture(path: str, size: int, n_frames: int) -> None:
    """Tiny MJPG fixture the lane 'monitors': intra-only codec, so both
    the seeked window decode (full path) and the sequential read
    (streaming path) are frame-exact and byte-identical."""
    import cv2
    import numpy as np

    wr = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"MJPG"), 30.0,
                         (size, size))
    if not wr.isOpened():
        raise RuntimeError("cv2 VideoWriter (MJPG) unavailable")
    rng = np.random.default_rng(7)
    base = rng.integers(0, 255, (size, size, 3), np.uint8)
    for i in range(n_frames):
        wr.write(np.roll(base, 3 * i, axis=1))
    wr.release()


def bench_stream(args) -> dict:
    """The STREAM lane (streaming/; docs/SERVING.md § streaming):
    incremental streaming inference vs the one-shot full-recompute
    baseline, per emitted label, on a live-stream monitoring workload.

    What each path pays PER LABEL (the issue the subsystem exists for):
    the full-recompute baseline re-decodes the whole T-frame window from
    the stream (the reference one-shot serving shape: every request is an
    independent clip), re-preprocesses it, ships it host->device, and
    recomputes the whole backbone; the incremental path reads only the
    *s* new frames from the open capture, ships those, and advances the
    device-resident ring (token families skip re-embedding the cached
    window too). Both paths are measured end to end on this host and
    decomposed (decode/serve ms) in the record.

    Proof obligations baked into the record (asserted by --smoke):
    - PARITY: incremental advance logits match the full-clip recompute
      over the same window, every measured round;
    - zero post-warmup recompiles across session advances (the
      per-compiled-step jit cache sizes stay flat at 1);
    - `stream_incremental_speedup` >= 1.5 at stride <= T/4 with the
      per-advance H2D payload cut >= 4x (exact byte ratio);
    - `stream_p99_ms` from an open-loop STREAM load run (heavy-tail
      durations, per-session label-latency honesty) through the
      continuous-batching scheduler, zero non-shed failures;
    - trunk-reuse sub-lane (docs/SERVING.md § trunk-reuse): a causal-
      masked backbone served with trunk=full vs trunk=causal KV rings,
      `stream_trunk_speedup` >= 2x decode-inclusive per label, KV parity
      <= tol against the full-recompute-under-the-same-mask replay, flat
      caches, and the evaluate() top-1 delta gate vs the bidirectional
      baseline — past the gate the lane REFUSES the speedup and
      headlines the delta + refusal instead.

    A non-smoke run that fell back to CPU refuses to headline (suspect),
    per the standing bench rule; CPU smoke numbers are plumbing
    verdicts, never device claims."""
    import shutil
    import tempfile

    import cv2
    import jax
    import numpy as np

    from pytorchvideo_accelerate_tpu.config import ModelConfig
    from pytorchvideo_accelerate_tpu.data.decode import decode_span
    from pytorchvideo_accelerate_tpu.fleet import Scheduler, StreamLoadGen
    from pytorchvideo_accelerate_tpu.models import create_model
    from pytorchvideo_accelerate_tpu.obs import memory as obs_memory
    from pytorchvideo_accelerate_tpu.serving.engine import InferenceEngine
    from pytorchvideo_accelerate_tpu.serving.stats import ServingStats
    from pytorchvideo_accelerate_tpu.streaming import StreamingEngine

    # arm the memory ledger BEFORE the engines are built: weight pins,
    # compiled-bucket caches, and session ring pools register as they
    # allocate, so the lane's hbm_* keys attribute real lane bytes (and
    # SessionTable admission consumes measured bytes where the backend
    # exposes memory_stats — the declared estimate elsewhere)
    obs_memory.configure()

    shape = STREAM_SMOKE if args.smoke else STREAM_FULL
    T, S = shape["window"], shape["stride"]
    crop, cam, n_sess = shape["crop"], shape["cam"], shape["sessions"]
    rounds, warmup = shape["rounds"], shape["warmup"]
    platform = jax.devices()[0].platform
    num_classes = 16

    cfg = ModelConfig(name="videomae_t", num_classes=num_classes,
                      dropout_rate=0.0)
    model = create_model(cfg, "fp32")
    variables = model.init(
        jax.random.key(0), np.zeros((1, T, crop, crop, 3), np.float32))
    engine = InferenceEngine(model, variables["params"],
                             variables.get("batch_stats", {}),
                             num_classes=num_classes,
                             max_batch_size=n_sess,
                             model_name="videomae_t")
    stream = StreamingEngine(engine, session_budget_mb=64.0,
                             session_ttl_s=120.0, name="bench")

    workdir = tempfile.mkdtemp(prefix="pva_stream_")
    try:
        n_frames = max(
            T + (rounds + warmup + 2) * S,
            shape["trunk_window"]
            + (shape["trunk_rounds"] + shape["trunk_warmup"] + 2) * S,
        ) + 8
        fixture = os.path.join(workdir, "stream.avi")
        _write_stream_fixture(fixture, cam, n_frames)
        # pre-compile every (op, bucket) stream step for the lane's
        # geometry + stride up front: a compile must never ride a
        # measured round OR a loadgen arrival (the first lone session at
        # a fresh bucket would otherwise stall the flush thread)
        n_warm = stream.warmup_stream(T, crop, crop, 3, S)
        log(f"[stream] warmed {n_warm} compiled stream steps over "
            f"buckets {engine.buckets}")

        def prep(frames_u8, size=crop):
            # the real client-side preprocess: camera-res -> model-res
            # resize + [0,1] float staging, per frame
            out = np.empty((frames_u8.shape[0], size, size, 3), np.float32)
            for i, f in enumerate(frames_u8):
                out[i] = cv2.resize(f, (size, size),
                                    interpolation=cv2.INTER_AREA)
            return out / 255.0

        # per-session streaming clients: one OPEN capture each (sequential
        # reads — a live stream never re-decodes delivered frames), offset
        # start positions so windows differ across sessions
        sids = [f"cam{i}" for i in range(n_sess)]
        caps, heads, windows = {}, {}, {}
        for i, sid in enumerate(sids):
            caps[sid] = cv2.VideoCapture(fixture)
            start = i  # phase offset
            if start:
                caps[sid].set(cv2.CAP_PROP_POS_FRAMES, start)
            frames = []
            for _ in range(T):
                ok, f = caps[sid].read()
                if not ok:
                    raise RuntimeError(
                        f"fixture unreadable at session setup ({sid})")
                frames.append(f[:, :, ::-1])
            heads[sid] = start + T  # index one past the newest frame
            windows[sid] = prep(np.stack(frames))

        # establish every session + warm the full-path bucket BEFORE
        # timing: compiles must never ride a measured round
        est = stream.advance_batch(
            [{"sid": sid, "window": windows[sid], "stride": S}
             for sid in sids])
        full0 = stream.full_recompute(
            np.stack([windows[s] for s in sids]))
        parity_max = float(max(
            np.max(np.abs(np.asarray(est[i]) - full0[i]))
            for i in range(n_sess)))

        def advance_round():
            """One label per session, both paths; returns per-path ms +
            parity delta."""
            t0 = time.perf_counter()
            items = []
            for sid in sids:
                fr = []
                for _ in range(S):
                    ok, f = caps[sid].read()
                    if not ok:
                        raise RuntimeError("fixture exhausted")
                    fr.append(f[:, :, ::-1])
                new = prep(np.stack(fr))
                # the resendable window: client-maintained, part of the
                # streaming client's honest per-label work
                windows[sid] = np.concatenate([windows[sid][S:], new], 0)
                heads[sid] += S
                items.append({"sid": sid, "frames": new})
            t_dec_inc = time.perf_counter() - t0
            out = stream.advance_batch(items)
            t_inc = time.perf_counter() - t0

            t0 = time.perf_counter()
            decoded = {}
            for sid in sids:
                # the one-shot baseline decodes its whole window per
                # label (seeked span decode — the stateless-request shape)
                u8 = decode_span(fixture, (heads[sid] - T) / 30.0,
                                 heads[sid] / 30.0, max_frames=T)
                decoded[sid] = prep(u8)
            t_dec_full = time.perf_counter() - t0
            stacked = np.stack([decoded[s] for s in sids])
            full = stream.full_recompute(stacked)
            t_full = time.perf_counter() - t0
            # the seeked decode must reproduce the sequential stream
            # exactly (intra-only codec) — this doubles as the stream-
            # position bookkeeping check
            for i, sid in enumerate(sids):
                if not np.array_equal(decoded[sid], windows[sid]):
                    raise RuntimeError(
                        f"seeked window decode diverged from the "
                        f"sequential stream for {sid} at head "
                        f"{heads[sid]} (position bookkeeping broken?)")
            delta = float(max(
                np.max(np.abs(np.asarray(out[i]) - full[i]))
                for i in range(n_sess)))
            return (t_inc * 1e3, t_full * 1e3, t_dec_inc * 1e3,
                    t_dec_full * 1e3, delta)

        for _ in range(warmup):
            advance_round()
        cache_before = stream.compiled_stream_cache_sizes()
        keys_before = set(stream.compiled_stream_keys())

        inc_ms, full_ms, dec_inc_ms, dec_full_ms = [], [], [], []
        for _ in range(rounds):
            ti, tf, di, df, delta = advance_round()
            inc_ms.append(ti)
            full_ms.append(tf)
            dec_inc_ms.append(di)
            dec_full_ms.append(df)
            parity_max = max(parity_max, delta)

        cache_after = stream.compiled_stream_cache_sizes()
        recompiles = sum(
            (cache_after.get(k) or 1) - (cache_before.get(k) or 1)
            for k in cache_before) + len(
                set(stream.compiled_stream_keys()) - keys_before)
        for cap in caps.values():
            cap.release()

        med_inc = statistics.median(inc_ms)
        med_full = statistics.median(full_ms)
        geom = stream.geom_key(T, crop, crop, 3, engine.input_dtype)
        h2d_frac = (stream.advance_h2d_bytes(geom, S)
                    / stream.full_h2d_bytes(geom))

        # open-loop STREAM load through the continuous-batching scheduler
        # (heavy-tail durations, windows attached = the re-establish-
        # anywhere contract), label p99 over completions
        stats = ServingStats(window=2048)
        sched = Scheduler(stream, max_queue=256, stats=stats,
                          realtime_deadline_ms=shape["slo_label_p99_ms"] * 4,
                          batch_max_wait_ms=2.0, name="stream-bench")
        try:
            gen = StreamLoadGen(
                sched.submit, stream_rate_sps=shape["lg_rate_sps"],
                duration_s=shape["lg_duration_s"], window=T, stride=S,
                frame_shape=(crop, crop, 3), advance_interval_s=S / 30.0,
                seed=0, mean_advances=6.0, max_advances=24)
            lg = gen.run()
        finally:
            sched.close()

        # ---- trunk-reuse sub-lane (docs/SERVING.md § trunk-reuse) ----
        # The KV-ring question, at a shape where the trunk dominates the
        # per-label cost (the main lane's tiny geometry is dispatch-bound
        # on the smoke host — a ratio there measures launch overhead, not
        # trunk compute): ONE causal-masked backbone (the shape a
        # `--model.attn_mask causal` finetune produces), served twice
        # over one engine. trunk=full re-runs the masked trunk over the
        # whole cached token window per advance; trunk=causal advances
        # the device-resident KV ring with only the new tubelets'
        # queries. Same decode, same H2D, same embed — the ratio is the
        # trunk-reuse win and nothing else.
        Tt, cropt = shape["trunk_window"], shape["trunk_crop"]
        tr_rounds, tr_warm = shape["trunk_rounds"], shape["trunk_warmup"]
        cfg_m = ModelConfig(name="videomae_t", num_classes=num_classes,
                            dropout_rate=0.0, attn_mask="causal")
        model_m = create_model(cfg_m, "fp32")
        vars_m = model_m.init(
            jax.random.key(0),
            np.zeros((1, Tt, cropt, cropt, 3), np.float32))
        eng_m = InferenceEngine(model_m, vars_m["params"],
                                vars_m.get("batch_stats", {}),
                                num_classes=num_classes,
                                max_batch_size=n_sess,
                                model_name="videomae_t")
        tr_full = StreamingEngine(eng_m, session_budget_mb=96.0,
                                  session_ttl_s=120.0,
                                  name="bench-trunk-full", trunk="full")
        tr_kv = StreamingEngine(eng_m, session_budget_mb=96.0,
                                session_ttl_s=120.0,
                                name="bench-trunk-kv", trunk="causal")
        n_tw = tr_full.warmup_stream(Tt, cropt, cropt, 3, S)
        n_tw += tr_kv.warmup_stream(Tt, cropt, cropt, 3, S)
        log(f"[stream] trunk sub-lane: warmed {n_tw} compiled steps at "
            f"window={Tt} crop={cropt}")

        tcaps, twin, thist = {}, {}, {}
        for i, sid in enumerate(sids):
            tcaps[sid] = cv2.VideoCapture(fixture)
            if i:
                tcaps[sid].set(cv2.CAP_PROP_POS_FRAMES, i)
            frames = []
            for _ in range(Tt):
                ok, f = tcaps[sid].read()
                if not ok:
                    raise RuntimeError("fixture exhausted at trunk "
                                       "sub-lane establish")
                frames.append(f[:, :, ::-1])
            twin[sid] = prep(np.stack(frames), cropt)
            thist[sid] = twin[sid]
        est_f = tr_full.advance_batch(
            [{"sid": s, "window": twin[s], "stride": S} for s in sids])
        est_k = tr_kv.advance_batch(
            [{"sid": s, "window": twin[s], "stride": S} for s in sids])
        # at establish the two trunks are the same banded function over
        # the same positions — a free cross-executable parity anchor
        trunk_par = float(max(
            np.max(np.abs(np.asarray(est_k[i]) - np.asarray(est_f[i])))
            for i in range(n_sess)))

        def trunk_round():
            """One label per session through BOTH trunks; decode once
            (both paths ship the same s new frames) and count it in each
            path's per-label cost — decode-inclusive end to end."""
            t0 = time.perf_counter()
            new = {}
            for sid in sids:
                fr = []
                for _ in range(S):
                    ok, f = tcaps[sid].read()
                    if not ok:
                        raise RuntimeError("fixture exhausted at trunk "
                                           "sub-lane rounds")
                    fr.append(f[:, :, ::-1])
                new[sid] = prep(np.stack(fr), cropt)
                twin[sid] = np.concatenate([twin[sid][S:], new[sid]], 0)
                thist[sid] = np.concatenate([thist[sid], new[sid]], 0)
            t_dec = time.perf_counter() - t0
            t0 = time.perf_counter()
            tr_full.advance_batch(
                [{"sid": s, "frames": new[s]} for s in sids])
            t_f = time.perf_counter() - t0
            t0 = time.perf_counter()
            out_k = tr_kv.advance_batch(
                [{"sid": s, "frames": new[s]} for s in sids])
            t_k = time.perf_counter() - t0
            return (t_dec + t_f) * 1e3, (t_dec + t_k) * 1e3, out_k

        for _ in range(tr_warm):
            trunk_round()
        tcaches = [(se, se.compiled_stream_cache_sizes(),
                    set(se.compiled_stream_keys()))
                   for se in (tr_full, tr_kv)]
        cost_f, cost_k, last_k = [], [], None
        for _ in range(tr_rounds):
            cf, ck, last_k = trunk_round()
            cost_f.append(cf)
            cost_k.append(ck)
        trunk_rec = sum(
            (se.compiled_stream_cache_sizes().get(k) or 1)
            - (before.get(k) or 1)
            for se, before, _ in tcaches for k in before) + sum(
            len(set(se.compiled_stream_keys()) - keys)
            for se, _, keys in tcaches)
        # the KV-trunk parity oracle is the full recompute UNDER THE
        # SAME MASK over the whole per-session history — cached K/V
        # legitimately attended context that has since left the ring, so
        # the trailing-window one-shot is not equivalent (engine.py
        # full_recompute_history)
        replay = tr_kv.full_recompute_history(
            np.stack([thist[s] for s in sids]), Tt)
        trunk_par = max(trunk_par, float(max(
            np.max(np.abs(np.asarray(last_k[i]) - replay[i]))
            for i in range(n_sess))))
        for cap in tcaps.values():
            cap.release()

        # evaluate() quality gate: top-1 accuracy DELTA vs the
        # bidirectional baseline on a fixed-seed synthetic eval, served
        # path included (establish + KV advances). The baseline is the
        # SAME weights with the mask off — the main lane's engine (same
        # init key; the mask adds no params), i.e. exactly what the
        # backbone answered before the banded-trunk finetune recipe.
        rng = np.random.default_rng(16)
        n_eval = shape["trunk_eval"]
        clips = rng.random((n_eval, Tt, cropt, cropt, 3)).astype(np.float32)
        steps = rng.random((n_eval, 2, S, cropt, cropt, 3)).astype(np.float32)
        labels = rng.integers(0, num_classes, n_eval)
        hits_base, hits_kv = 0, 0
        for lo in range(0, n_eval, n_sess):
            idx = list(range(lo, min(lo + n_sess, n_eval)))
            evs = [f"ev{i}" for i in idx]
            tr_kv.advance_batch(
                [{"sid": s, "window": clips[i], "stride": S}
                 for s, i in zip(evs, idx)])
            win = {i: clips[i] for i in idx}
            out_k = None
            for a in range(2):
                out_k = tr_kv.advance_batch(
                    [{"sid": s, "frames": steps[i, a]}
                     for s, i in zip(evs, idx)])
                for i in idx:
                    win[i] = np.concatenate([win[i][S:], steps[i, a]], 0)
            for s in evs:
                tr_kv.end_session(s)
            base = engine.predict(
                {"video": np.stack([win[i] for i in idx])})
            for j, i in enumerate(idx):
                hits_kv += int(np.argmax(np.asarray(out_k[j]))
                               == labels[i])
                hits_base += int(np.argmax(np.asarray(base[j]))
                                 == labels[i])
        trunk_delta = round(abs(hits_base - hits_kv) / n_eval, 4)

        med_tf = statistics.median(cost_f)
        med_tk = statistics.median(cost_k)
        out = {
            "stream_incremental_speedup": round(med_full / med_inc, 3),
            "stream_h2d_bytes_frac": round(h2d_frac, 4),
            "stream_p99_ms": lg["label_p99_ms"],
            "stream_parity_max_abs": round(parity_max, 6),
            "stream_parity": bool(parity_max <= STREAM_PARITY_TOL),
            "stream_recompiles": int(recompiles),
            # trunk-reuse sub-lane verdicts (docs/SERVING.md
            # § trunk-reuse): KV-ring advance vs the full-recompute-
            # under-the-same-mask replay, flat caches, and the
            # evaluate() top-1 delta vs the bidirectional baseline
            "stream_trunk_parity_max_abs": round(trunk_par, 6),
            "stream_trunk_parity": bool(trunk_par <= STREAM_PARITY_TOL),
            "stream_trunk_recompiles": int(trunk_rec),
            "stream_trunk_top1_delta": trunk_delta,
            "stream_trunk_top1_tol": STREAM_TRUNK_TOP1_TOL,
            "trunk_window": Tt,
            "trunk_crop": cropt,
            "trunk_eval_clips": int(n_eval),
            "label_ms_trunk_full": round(med_tf, 3),
            "label_ms_trunk_kv": round(med_tk, 3),
            # memory-ledger triple: the streaming ring pools + engine
            # weight pins registered above make this lane's attribution
            # meaningful on any host (estimate-labeled off device)
            **hbm_headline(),
            "stream_sessions": n_sess,
            "window": T,
            "stride": S,
            "label_ms_full": round(med_full, 3),
            "label_ms_incremental": round(med_inc, 3),
            "decode_ms_full": round(statistics.median(dec_full_ms), 3),
            "decode_ms_incremental": round(statistics.median(dec_inc_ms), 3),
            "loadgen": {k: lg[k] for k in
                        ("streams", "advances_offered", "completed",
                         "failed", "shed", "label_p50_ms", "label_p99_ms",
                         "max_arrival_lag_ms", "open_loop_ok")},
            "stream_failed": int(lg["failed"]),
            "open_loop_ok": lg["open_loop_ok"],
            "slo_label_p99_ms": shape["slo_label_p99_ms"],
            "platform": platform,
            "smoke": bool(args.smoke),
            # a non-smoke stream lane on CPU is not a serving measurement
            # — refuse to headline (finalize drops the perf keys)
            "suspect": platform == "cpu" and not args.smoke,
        }
        # the refusal half of the quality gate: a masked trunk whose
        # top-1 drifted past the gate headlines the delta and the
        # refusal INSTEAD of the speedup — a faster wrong answer is not
        # a win (docs/SERVING.md § trunk-reuse)
        if trunk_delta <= STREAM_TRUNK_TOP1_TOL:
            out["stream_trunk_speedup"] = round(med_tf / med_tk, 3)
        else:
            out["stream_trunk_refused"] = (
                f"top-1 delta {trunk_delta} vs the bidirectional "
                f"baseline breaches the {STREAM_TRUNK_TOP1_TOL} quality "
                "gate; speedup refused — finetune with the matching "
                "--model.attn_mask (docs/SERVING.md § trunk-reuse)")
        log(f"[stream] {json.dumps(out)}")
        return out
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def probe_device(probe_attempts: list, timeout: int = 240) -> bool:
    """Can a fresh process enumerate the TPU and run one op? Timestamped
    evidence either way; also appended to .probe_log.jsonl."""
    rec = {"ts": _utcnow(), "timeout_s": timeout}
    t0 = time.time()
    code = ("import jax, numpy as np\n"
            "d = jax.devices()[0]\n"
            "assert d.platform != 'cpu', d.platform\n"
            "x = jax.device_put(np.ones((128, 128), np.float32), d)\n"
            "jax.jit(lambda a: a @ a)(x).block_until_ready()\n"
            "print(d.platform, d.device_kind)\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
        if r.returncode == 0:
            rec.update(ok=True, elapsed_s=round(time.time() - t0, 1),
                       device=r.stdout.strip())
        else:
            rec.update(ok=False, elapsed_s=round(time.time() - t0, 1),
                       error=(r.stderr.strip() or "nonzero exit")[-200:])
    except subprocess.TimeoutExpired:
        rec.update(ok=False, elapsed_s=round(time.time() - t0, 1),
                   error="timeout (backend init wedged)")
    except Exception as e:
        rec.update(ok=False, error=f"{type(e).__name__}: {e}")
    probe_attempts.append(rec)
    log(f"[probe] {rec}")
    try:
        with open(os.path.join(HERE, ".probe_log.jsonl"), "a") as f:
            f.write(json.dumps({"probe": "bench", **rec}) + "\n")
    except OSError:
        pass
    return bool(rec.get("ok"))


def _model_timeout(args):
    """0 = no limit (matches the historical --per_model_timeout contract)."""
    return args.per_model_timeout if args.per_model_timeout > 0 else None


def run_child(target: str, args, smoke: bool, timeout) -> dict:
    """One bench in a disposable subprocess (own process group; killed
    wholesale on timeout so a wedged backend can't hang the round);
    `timeout=None` = no limit."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child", target,
           "--steps", str(args.steps), "--warmup", str(args.warmup),
           "--alpha", str(args.alpha), "--inputs", args.inputs]
    if smoke:
        cmd.append("--smoke")
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=sys.stderr,
                         text=True, start_new_session=True)
    try:
        out, _ = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except OSError:
            pass
        p.wait()
        log(f"[{target}] child killed after {timeout}s")
        return {"error": f"child timeout after {timeout}s", "smoke": smoke}
    if p.returncode != 0:
        return {"error": f"child exited {p.returncode}", "smoke": smoke}
    from pytorchvideo_accelerate_tpu.utils.forcehost import last_json_line

    res = last_json_line(out)
    return res if res is not None else {"error": "no JSON from child",
                                        "smoke": smoke}


def child_main(args) -> None:
    """--child entry: run ONE bench and print its JSON as the last line."""
    if args.child == "__multichip__" and args.smoke:
        # forced-host slice: must land in XLA_FLAGS before the first device
        # touch (jax is imported, but the backend only latches the flag at
        # client init — the dryrun_multichip pattern)
        from pytorchvideo_accelerate_tpu.utils.forcehost import forced_host_env

        os.environ["XLA_FLAGS"] = forced_host_env(
            MULTICHIP_FORCED_DEVICES)["XLA_FLAGS"]
    if args.child == "__pipeline__" and args.smoke:
        # forced-host slice for the PIPELINE lane (same latching rule)
        from pytorchvideo_accelerate_tpu.utils.forcehost import forced_host_env

        os.environ["XLA_FLAGS"] = forced_host_env(
            PIPELINE_FORCED_DEVICES)["XLA_FLAGS"]
    if args.child == "__fleet__" and args.smoke and FLEET_SMOKE["devices"]:
        # SERVE_FLEET multi-device CI: each replica gets its own forced
        # CPU device, so routing/swap run against genuinely disjoint
        # meshes (utils/forcehost.py, same latching rule as multichip)
        from pytorchvideo_accelerate_tpu.utils.forcehost import forced_host_env

        os.environ["XLA_FLAGS"] = forced_host_env(
            FLEET_SMOKE["devices"])["XLA_FLAGS"]
    jax = _setup_jax(args.smoke, child=args.child)
    if args.smoke:
        args.steps, args.warmup = min(args.steps, 3), 1

    if args.child == "__trainer__":
        res = bench_trainer(args)
    elif args.child == "__multichip__":
        res = bench_multichip(args)
    elif args.child == "__pipeline__":
        res = bench_pipeline(args)
    elif args.child == "__fleet__":
        res = bench_fleet(args)
    elif args.child == "__fleet_auto__":
        res = bench_fleet_auto(args)
    elif args.child == "__kbench__":
        res = bench_kbench(args)
    elif args.child == "__stream__":
        res = bench_stream(args)
    else:
        devices = jax.devices()
        n_chips = len(devices)
        peak = peak_tflops(devices[0])
        log(f"devices: {n_chips} x {devices[0].device_kind} "
            f"({devices[0].platform}), bf16 peak "
            f"{f'{peak:.0f} TFLOP/s/chip' if peak else 'unknown'}")
        res = bench_model(args.child, WORKLOADS[args.child], args, n_chips)
        res["n_chips"] = n_chips
    print("\n" + json.dumps(res))
    sys.stdout.flush()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default="default",
                    help="comma list of " + ",".join(WORKLOADS)
                         + "; 'default' = the BASELINE four ("
                         + ",".join(DEFAULT_MODELS) + "); 'all' = every "
                         "workload incl. the r5 zoo additions")
    ap.add_argument("--alpha", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--inputs", choices=("u8", "f32"), default="u8",
                    help="synthetic batch staging: raw uint8 + in-graph "
                         "normalize (the host_cast=u8 production path, 4x "
                         "less transfer) or float32 (r1-r4 staging)")
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--trainer", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="also run Trainer.fit() on synthetic data and report "
                         "its throughput vs the raw step (hot-loop overhead)")
    ap.add_argument("--multichip", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="MULTICHIP scaling lane: 1->N clips/s/chip through "
                         "the 2-D (data, model) train mesh, with loss-parity "
                         "and mesh-reshape checkpoint probes; forced-host "
                         "CPU devices in smoke mode (never device numbers)")
    ap.add_argument("--pipeline", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="PIPELINE lane: pipeline-parallel VideoMAE "
                         "pretrain — P=1 vs P=2/4 fp32 loss-parity at a "
                         "fixed global batch, analytic + measured "
                         "fill/drain bubble fraction, pipelined clips/s/"
                         "chip, donation through the stage scan; forced-"
                         "host CPU devices in smoke mode (--no-pipeline "
                         "skips)")
    ap.add_argument("--data", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="host input-pipeline microbench (decode vs cache vs "
                         "loader clips/sec; CPU-real numbers regardless of "
                         "device-timing trustworthiness); --no-data skips")
    ap.add_argument("--dataplane", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="DATA_PLANE lane: local loader vs N remote decode-"
                         "worker processes on the same source/seed; "
                         "headlines dataplane_cps / "
                         "dataplane_input_wait_frac / dataplane_workers, "
                         "parity-gated byte-identical (--no-dataplane "
                         "skips)")
    ap.add_argument("--serve-smoke", dest="serve_smoke",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="serving-lane smoke: engine + micro-batcher under "
                         "a synthetic client; p50/p99 request latency and "
                         "batch-fill ratio on the headline line "
                         "(--no-serve-smoke skips)")
    ap.add_argument("--fleet", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="SERVE_FLEET lane: >=2 engine replicas behind the "
                         "fleet router under open-loop load with a "
                         "mid-load checkpoint hot-swap; headlines "
                         "serve_rps / serve_p99_ms_under_load / "
                         "swap_blackout_ms / fleet_shed_frac "
                         "(--no-fleet skips)")
    ap.add_argument("--fleet-auto", dest="fleet_auto",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="FLEET_AUTO lane: the fleet-intelligence control "
                         "loops — SLO-driven autoscaling under a traffic "
                         "step, session-safe scale-down, multi-model "
                         "budget shed, canary auto-rollback; headlines "
                         "autoscale_converge_s / fleet_scaledown_shed_frac "
                         "/ canary_rollback / fleet_models_served "
                         "(--no-fleet-auto skips)")
    ap.add_argument("--stream", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="STREAM lane: incremental streaming inference "
                         "(device-resident session rings) vs the one-shot "
                         "full-recompute baseline per emitted label; "
                         "headlines stream_incremental_speedup / "
                         "stream_h2d_bytes_frac / stream_p99_ms, "
                         "parity-gated (--no-stream skips)")
    ap.add_argument("--kbench", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="kernel-microbench lane (pva-tpu-kbench): fused "
                         "Pallas/folded kernels vs their XLA references "
                         "at real slowfast/x3d shapes; per-kernel "
                         "same-backend speedup keys on the headline, "
                         "parity gated (--no-kbench skips)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-safe shapes for harness verification")
    ap.add_argument("--per_model_timeout", type=int, default=900,
                    help="seconds before a model's child bench is killed "
                         "(0 = no limit)")
    ap.add_argument("--probe_timeout", type=int, default=240)
    ap.add_argument("--child", default="", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child:
        child_main(args)
        return

    # The parent must NEVER touch devices: a wedged axon init inside this
    # process would lose the whole round. All device work happens in
    # children; the parent pins itself to CPU before any jax import can act.
    _setup_jax(smoke=True)

    if args.smoke:
        # bench-contract guarantee (fails fast, before any child spends
        # minutes): the package tree must be pva-tpu-lint clean — the
        # static half of the hazard contract whose runtime half is the
        # train_recompiles == 0 assert below. docs/STATIC_ANALYSIS.md.
        from pytorchvideo_accelerate_tpu.analysis import run_lint

        lint_findings = run_lint(
            [os.path.join(HERE, "pytorchvideo_accelerate_tpu")])
        assert not lint_findings, (
            "bench --smoke requires a lint-clean tree; pva-tpu-lint found:\n"
            + "\n".join(f.format() for f in lint_findings[:20]))
        log(f"[lint] pva-tpu-lint clean ({len(lint_findings)} findings)")
        # the dynamic half of the same contract: one short pva-tpu-tsan
        # stress pass (lockset races + lock-order cycles over the threaded
        # layers) must come back clean before any child spends minutes.
        # Runs in the parent (CPU-pinned, like the serving lane).
        from pytorchvideo_accelerate_tpu.analysis.tsan_report import (
            finding_count,
            format_report,
            publish,
            run_stress,
        )

        tsan_report = run_stress(smoke=True, log=log)
        publish(tsan_report)
        tsan_findings = finding_count(tsan_report)
        log(f"[tsan] pva-tpu-tsan: {tsan_findings} finding(s) "
            f"in {tsan_report['elapsed_s']}s")
        if tsan_findings:
            log(format_report(tsan_report))
        assert tsan_findings == 0, (
            "bench --smoke requires a tsan-clean stress pass; pva-tpu-tsan "
            f"found {tsan_findings} race/lock-cycle finding(s) (report "
            "logged above; see docs/STATIC_ANALYSIS.md)")
        # the resilience leg of the same contract (docs/RELIABILITY.md):
        # the pva-tpu-chaos seeded fault-injection scenario — decode
        # faults, a mid-write checkpoint failure, a tracker outage, a
        # mid-epoch SIGTERM, serving overload — must RECOVER everywhere.
        # Gated here, before any child spends minutes (the lint/tsan
        # pattern). Runs in the parent: CPU-pinned, like the tsan pass.
        from pytorchvideo_accelerate_tpu.reliability.chaos import (
            finding_count as chaos_finding_count,
            format_report as chaos_format,
            publish as chaos_publish,
            run_scenario as run_chaos,
        )

        chaos_report = run_chaos(smoke=True, log=log)
        chaos_publish(chaos_report)
        chaos_findings = chaos_finding_count(chaos_report)
        log(f"[chaos] pva-tpu-chaos: {chaos_findings} finding(s) "
            f"in {chaos_report['elapsed_s']}s")
        if chaos_findings:
            log(chaos_format(chaos_report))
        assert chaos_findings == 0, (
            "bench --smoke requires a chaos-clean scenario; pva-tpu-chaos "
            f"found {chaos_findings} unrecovered fault(s) (report logged "
            "above; see docs/RELIABILITY.md)")
        # the compiled-graph leg of the same contract (docs/
        # STATIC_ANALYSIS.md § graphcheck): the four jaxpr/HLO passes —
        # donation aliasing, dtype policy, sharding propagation,
        # analytic-vs-costmodel FLOPs — over the REAL train/eval/serve
        # steps must come back clean, and the train step must be
        # VERIFIED donated (every declared donation aliased, zero
        # donatable state leaves undeclared). Gated here, before any
        # child spends minutes (the lint/tsan/chaos pattern).
        from pytorchvideo_accelerate_tpu.analysis.graphcheck import (
            finding_count as graphcheck_finding_count,
            format_report as graphcheck_format,
            run_graphcheck,
        )

        graphcheck_report = run_graphcheck(smoke=True, log=log)
        graphcheck_findings = graphcheck_finding_count(graphcheck_report)
        log(f"[graphcheck] pva-tpu-graphcheck: {graphcheck_findings} "
            f"finding(s) in {graphcheck_report['elapsed_s']}s "
            f"(donation_verified="
            f"{graphcheck_report['donation_verified']})")
        if graphcheck_findings:
            log(graphcheck_format(graphcheck_report))
        assert graphcheck_findings == 0, (
            "bench --smoke requires a graphcheck-clean tree; "
            f"pva-tpu-graphcheck found {graphcheck_findings} finding(s) "
            "(report logged above; see docs/STATIC_ANALYSIS.md)")
        assert graphcheck_report["donation_verified"] is True, (
            "bench --smoke requires a VERIFIED-donated train step: the "
            "donation pass reports declared-but-unaliased or "
            "undeclared-donatable state leaves (see "
            "docs/STATIC_ANALYSIS.md § donation)")
        # collective-schedule divergence gate (docs/STATIC_ANALYSIS.md
        # § spmdcheck): the static pass over the hot modules — collectives
        # under host-divergent predicates, asymmetric branch arms, skip
        # paths past a later collective, checkpoint-write discipline, and
        # the collective_section coverage audit — must come back clean
        # before any child spends minutes. The multi-host pod runtime's
        # precondition rides the same lint/tsan/chaos/graphcheck pattern.
        from pytorchvideo_accelerate_tpu.analysis.spmdcheck import (
            finding_count as spmdcheck_finding_count,
            format_report as spmdcheck_format,
            run_spmdcheck,
        )

        spmdcheck_report = run_spmdcheck(log=log)
        spmdcheck_findings = spmdcheck_finding_count(spmdcheck_report)
        log(f"[spmdcheck] pva-tpu-spmdcheck: {spmdcheck_findings} "
            f"finding(s) in {spmdcheck_report['elapsed_s']}s")
        if spmdcheck_findings:
            log(spmdcheck_format(spmdcheck_report))
        assert spmdcheck_findings == 0, (
            "bench --smoke requires an spmdcheck-clean tree; "
            f"pva-tpu-spmdcheck found {spmdcheck_findings} finding(s) "
            "(report logged above; see docs/STATIC_ANALYSIS.md "
            "§ spmdcheck)")

    user_smoke = args.smoke
    probe_attempts: list = []
    partial_path = os.path.join(HERE, "bench_partial.json")
    results: dict = {}
    extras: dict = {"probe_attempts": probe_attempts}
    if user_smoke:
        extras["tsan_findings"] = tsan_findings
        extras["chaos_findings"] = chaos_findings
        extras["graphcheck_findings"] = graphcheck_findings
        extras["spmdcheck_findings"] = spmdcheck_findings

    def flush_partial():
        try:
            with open(partial_path, "w") as f:
                json.dump({"results": results, **extras}, f, indent=1)
        except OSError:
            pass

    device_ok = False
    if not user_smoke:
        device_ok = probe_device(probe_attempts, args.probe_timeout)
        if not device_ok:
            log("TPU unreachable on first probe; will re-probe between "
                "models — CPU smoke numbers are NOT device numbers")

    if args.models == "default":
        names = list(DEFAULT_MODELS)
    elif args.models == "all":
        names = list(WORKLOADS)
    else:
        names = args.models.split(",")

    def bench_one(name, smoke):
        # smoke children are capped tighter (tiny shapes) but still honor
        # the user's limit, including 0 = no limit
        timeout = (_model_timeout(args) if not smoke
                   else (min(args.per_model_timeout, 600)
                         if args.per_model_timeout > 0 else None))
        res = run_child(name, args, smoke, timeout)
        results[name] = res
        flush_partial()
        return res

    for i, name in enumerate(names):
        if not user_smoke and not device_ok and probe_attempts:
            # re-probe (shorter) before each model once the first probe
            # failed — the tunnel demonstrably comes and goes within a round
            if i > 0:
                device_ok = probe_device(probe_attempts,
                                         min(args.probe_timeout, 120))
        res = bench_one(name, smoke=user_smoke or not device_ok)
        if not user_smoke and device_ok and "error" in res:
            # device attempt wedged/died: don't trust the tunnel until a
            # fresh probe says otherwise; record a smoke fallback now
            log(f"[{name}] device bench failed; falling back to smoke")
            device_ok = False
            results[name + "__device_error"] = res
            bench_one(name, smoke=True)

    # late recovery: any model that had to run as CPU smoke gets retried on
    # the device — whether the tunnel is already back (mid-round recovery)
    # or comes back for one final probe now
    if not user_smoke:
        # platform missing covers error-only results (both children died):
        # those deserve a device retry too
        needs_retry = [n for n in names
                       if results.get(n, {}).get("platform", "cpu") == "cpu"]
        if needs_retry and not device_ok:
            device_ok = probe_device(probe_attempts, args.probe_timeout)
        for name in needs_retry:
            if not device_ok:
                break
            log(f"[{name}] retrying on recovered device")
            res = run_child(name, args, False, _model_timeout(args))
            if "error" not in res:
                results[name + "__smoke_fallback"] = results[name]
                results[name] = res
            else:
                # model-specific failure or tunnel loss? a probe tells them
                # apart — only a dead tunnel should stop the other retries
                device_ok = probe_device(probe_attempts, 120)
            flush_partial()

    if args.trainer:
        # device only when the tunnel is known-good AND the flagship number
        # it will be compared against (when benched at all) is a device number
        flag = results.get("slowfast_r50")
        flag_mode_smoke = (user_smoke or not device_ok
                           or (flag is not None
                               and flag.get("platform", "cpu") == "cpu"))
        tr = run_child("__trainer__", args, flag_mode_smoke,
                       _model_timeout(args))
        if "trainer_cps_chip" in tr:
            extras["trainer_cps_chip"] = round(tr["trainer_cps_chip"], 3)
            if tr.get("input_wait_frac") is not None:
                # time fit()'s step loop spent blocked on input: the proof
                # (or refutation) that device prefetch overlaps H2D with
                # compute — << 1 is the healthy reading
                extras["trainer_input_wait_frac"] = round(
                    tr["input_wait_frac"], 4)
            if tr.get("mfu") is not None:
                extras["trainer_mfu"] = round(tr["mfu"], 4)
            if tr.get("mfu_analytic") is not None:
                # the analytic-counter MFU + its provenance labels — the
                # headline keys the --smoke gate asserts non-null (the
                # "honest MFU" leg of ROADMAP item 1). The peak-source
                # label rides too: a "measured" denominator is a matmul-
                # rate proxy, and a round must never read as a datasheet
                # fraction (utils/hw.resolve_peak's contract)
                extras["mfu_analytic"] = round(tr["mfu_analytic"], 4)
                if tr.get("mfu_source"):
                    extras["mfu_source"] = tr["mfu_source"]
                if tr.get("mfu_peak_source"):
                    extras["mfu_peak_source"] = tr["mfu_peak_source"]
            # registry-sourced step-time breakdown (obs/): per-step wall
            # time, input-blocked fraction, and H2D copy time — the
            # telemetry-spine successors of the ad-hoc perf dict
            for key in ("obs_step_s", "obs_input_wait_frac", "obs_h2d_s"):
                if tr.get(key) is not None:
                    extras[key] = round(tr[key], 6)
            if "train_recompiles" in tr:
                # steady-state recompiles seen by fit()'s hot loop —
                # asserted zero in --smoke (the recompile-hazard
                # contract); None = the jit cache probe is unavailable
                # on this jax (reported as unknown, never a lying 0)
                r = tr["train_recompiles"]
                extras["train_recompiles"] = None if r is None else int(r)
            for key in ("guard_rollbacks", "quarantined_clips"):
                # self-healing-guard verdicts (reliability/guard.py) —
                # asserted 0 in --smoke: a clean synthetic run that rolls
                # back or quarantines is a guard false positive
                if tr.get(key) is not None:
                    extras[key] = int(tr[key])
            # memory-ledger triple (pva-tpu-hbm): the trainer lane is the
            # flagship device process, so ITS ledger read headlines; the
            # provenance label always rides with the bytes — an
            # "estimate" peak is a CPU-host attribution sum, never a
            # device claim (perfdiff refuses suspect rounds wholesale)
            for key in ("hbm_peak_bytes", "hbm_attributed_frac",
                        "hbm_source"):
                if tr.get(key) is not None:
                    extras[key] = tr[key]
            raw = (results.get("slowfast_r50") or {}).get(
                "clips_per_sec_per_chip")
            # only a same-mode comparison is meaningful
            if raw and (results["slowfast_r50"].get("smoke")
                        == tr.get("smoke")):
                extras["trainer_vs_rawstep"] = round(
                    tr["trainer_cps_chip"] / raw, 3)
        else:
            extras["trainer_error"] = tr.get("error", "unknown")
        flush_partial()

    if args.multichip:
        # MULTICHIP lane: same child-isolation rules as the model benches
        # (a wedged 8-way compile loses the lane, not the round). Runs
        # forced-host (honest CPU parity, never headlined as device
        # numbers) whenever the round is smoke or the tunnel is down.
        mc = run_child("__multichip__", args, user_smoke or not device_ok,
                       _model_timeout(args))
        extras["multichip"] = mc  # full record -> bench_partial.json
        if "error" in mc:
            extras["multichip_error"] = str(mc["error"])[:120]
        else:
            # numerics verdicts always ride the headline
            extras["mesh_parity"] = mc.get("mesh_parity")
            if "mesh_ckpt_portable" in mc:
                extras["mesh_ckpt_portable"] = mc["mesh_ckpt_portable"]
            if mc.get("train_recompiles") is not None:
                extras["multichip_train_recompiles"] = int(
                    mc["train_recompiles"])
            # spmdcheck dynamic verdicts ride like the numerics ones
            # (verdicts, not perf — the suspect refusal never hides them)
            if mc.get("spmd_schedule_divergence") is not None:
                extras["spmd_schedule_divergence"] = int(
                    mc["spmd_schedule_divergence"])
            if mc.get("spmd_divergence_detected") is not None:
                extras["spmd_divergence_detected"] = bool(
                    mc["spmd_divergence_detected"])
            # perf numbers only when trustworthy: a non-smoke lane that
            # landed on CPU is a lying tunnel, not a scaling curve
            if mc.get("suspect"):
                extras["multichip_error"] = (
                    "no trustworthy device numbers for the multichip lane "
                    "(cpu fallback); parity verdicts retained")
            else:
                extras["multichip_cps_per_chip"] = mc.get("cps_per_chip")
                extras["multichip_forced_host"] = bool(
                    mc.get("forced_host"))
                if mc.get("multichip_mfu") is not None:
                    extras["multichip_mfu"] = mc["multichip_mfu"]
                if mc.get("multichip_mfu_analytic") is not None:
                    extras["multichip_mfu_analytic"] = mc[
                        "multichip_mfu_analytic"]
                if mc.get("multichip_mfu_peak_source"):
                    extras["multichip_mfu_peak_source"] = mc[
                        "multichip_mfu_peak_source"]
        flush_partial()

    if args.pipeline:
        # PIPELINE lane: child-isolated like the multichip lane (a wedged
        # stage compile loses the lane, not the round); forced-host in
        # smoke, and the same refusal rule — a non-smoke CPU fallback
        # headlines pipeline_error INSTEAD of the perf keys while the
        # parity verdict rides regardless
        pc = run_child("__pipeline__", args, user_smoke or not device_ok,
                       _model_timeout(args))
        extras["pipeline"] = pc  # full record -> bench_partial.json
        if "error" in pc:
            extras["pipeline_error"] = str(pc["error"])[:120]
        else:
            extras["pipeline_parity"] = pc.get("pipeline_parity")
            if pc.get("pipeline_donation_verified") is not None:
                extras["pipeline_donation_verified"] = bool(
                    pc["pipeline_donation_verified"])
            if pc.get("train_recompiles") is not None:
                extras["pipeline_train_recompiles"] = int(
                    pc["train_recompiles"])
            if pc.get("suspect"):
                extras["pipeline_error"] = (
                    "no trustworthy device numbers for the pipeline lane "
                    "(cpu fallback); parity verdicts retained")
            else:
                for key in ("pipeline_cps_per_chip", "pipeline_bubble_frac",
                            "pipeline_bubble_frac_analytic",
                            "pipeline_stages"):
                    if pc.get(key) is not None:
                        extras[key] = pc[key]
        flush_partial()

    if args.data:
        # host-side benches run in the parent but bounded: a wedged decode
        # or forked worker must not break the one-JSON-line contract (the
        # final os._exit below reaps any stuck daemon thread)
        from concurrent.futures import ThreadPoolExecutor
        from concurrent.futures import TimeoutError as FutTimeout

        pool = ThreadPoolExecutor(max_workers=1)
        for key, fn in (("data_pipeline", bench_data),
                        ("transport_crossover", bench_transport_crossover)):
            try:
                extras[key] = pool.submit(fn, args).result(timeout=900)
            except FutTimeout:
                log(f"[{key}] timed out after 900s")
                extras[key] = {"error": "timeout after 900s"}
                pool.shutdown(wait=False)
                pool = ThreadPoolExecutor(max_workers=1)
            except Exception as e:
                log(f"[{key}] FAILED: {type(e).__name__}: {e}")
                extras[key] = {"error": f"{type(e).__name__}: {e}"}
        pool.shutdown(wait=False)
        # how many host cores feed one chip at the measured device rate?
        dp = extras.get("data_pipeline", {})
        flag = results.get("slowfast_r50", {})
        loader_cps = dp.get("loader_thread_clips_per_sec")
        chip_cps = flag.get("clips_per_sec_per_chip")
        if loader_cps and chip_cps and dp.get("num_workers"):
            per_worker = loader_cps / dp["num_workers"]
            dp["loader_clips_per_sec_per_worker"] = round(per_worker, 2)
            dp["workers_to_feed_one_chip"] = round(chip_cps / per_worker, 1)
            dp["chip_demand_clips_per_sec"] = chip_cps
            dp["chip_demand_is_smoke"] = bool(flag.get("smoke"))
        if loader_cps and dp.get("num_workers"):
            dp["feed_projection"] = feed_projection(dp)
        flush_partial()

    if args.dataplane:
        # DATA_PLANE lane (dataplane/bench.py): local loader vs N remote
        # decode workers — host-CPU-real numbers in the bench_data
        # tradition (trustworthy on any box, never device claims), run in
        # the parent but bounded so a wedged worker process can't break
        # the one-JSON-line contract. A DAEMON thread, not an executor:
        # concurrent.futures' atexit hook joins non-daemon workers, so an
        # abandoned-but-wedged lane would block interpreter exit on any
        # non-os._exit path (a failed smoke assert) and lose the round to
        # the driver's kill. The refusal rule mirrors the fleet lane: a
        # failed or parity-broken lane headlines dataplane_error INSTEAD
        # of the perf keys.
        import threading as _dp_threading

        from pytorchvideo_accelerate_tpu.dataplane.bench import (
            run_dataplane_bench,
        )

        _dp_out: dict = {}

        def _dp_lane():
            try:
                # deadline_s < the join timeout: the lane self-bounds (it
                # stops spawning worker processes between trials) BEFORE
                # this thread is abandoned — nothing can cancel it from
                # outside, and an abandoned lane would keep spawning
                _dp_out["result"] = run_dataplane_bench(
                    smoke=args.smoke, workers=2, deadline_s=480, log=log)
            except Exception as e:  # noqa: BLE001 - lane isolation
                _dp_out["result"] = {
                    "error": f"{type(e).__name__}: {e}"}

        _dp_thread = _dp_threading.Thread(target=_dp_lane, daemon=True,
                                          name="bench-dataplane")
        _dp_thread.start()
        _dp_thread.join(timeout=600)
        dpl = _dp_out.get("result") or {"error": "timeout after 600s"}
        extras["dataplane"] = dpl
        if "error" in dpl:
            log(f"[dataplane] lane failed: {dpl['error']}")
            # an abandoned lane must not leave decode-worker PROCESSES
            # burning CPU under the fleet/serving lanes measured next —
            # the exact cross-lane distortion this lane documents
            from pytorchvideo_accelerate_tpu.dataplane.feed import (
                reap_spawned_workers,
            )

            reaped = reap_spawned_workers()
            if reaped:
                log(f"[dataplane] reaped {reaped} orphaned worker "
                    "process(es) after lane failure")
            extras["dataplane_error"] = str(dpl["error"])[:120]
        elif not dpl.get("parity"):
            extras["dataplane_error"] = (
                "remote batch stream diverged from the local loader "
                "(see bench_partial.json dataplane record)")
        else:
            extras["dataplane_cps"] = dpl["dataplane_cps"]
            extras["dataplane_input_wait_frac"] = dpl[
                "dataplane_input_wait_frac"]
            extras["dataplane_workers"] = dpl["dataplane_workers"]
        flush_partial()

    if args.fleet:
        # SERVE_FLEET lane: child-isolated like the model benches (a
        # wedged warmup compile loses the lane, not the round); smoke mode
        # runs on a forced-host slice so the two replicas get disjoint
        # devices. A non-smoke run with the tunnel down falls back to a
        # CPU child, which refuses to headline (suspect) — the standing
        # no-CPU-numbers-as-device-numbers rule.
        fl = run_child("__fleet__", args, user_smoke or not device_ok,
                       _model_timeout(args))
        extras["fleet"] = fl  # full record -> bench_partial.json
        if "error" in fl:
            extras["fleet_error"] = str(fl["error"])[:120]
        elif fl.get("suspect"):
            extras["fleet_error"] = (
                "no trustworthy device numbers for the fleet lane "
                "(cpu fallback); see bench_partial.json")
        else:
            for key in ("serve_rps", "serve_p99_ms_under_load",
                        "swap_blackout_ms", "fleet_shed_frac",
                        "trace_sampled", "trace_overhead_frac"):
                if fl.get(key) is not None:
                    extras[key] = fl[key]
        flush_partial()

    if args.fleet_auto:
        # FLEET_AUTO lane: child-isolated like the fleet lane; the same
        # refusal rule — a failed or cpu-fallback lane headlines
        # fleet_auto_error INSTEAD of the control-loop perf keys, and the
        # verdict keys (canary_promoted / fleet_session_failures) ride
        # regardless: a refused round must still say whether the rollback
        # machinery and the re-home path held
        fa = run_child("__fleet_auto__", args, user_smoke or not device_ok,
                       _model_timeout(args))
        extras["fleet_auto"] = fa  # full record -> bench_partial.json
        if "error" in fa:
            extras["fleet_auto_error"] = str(fa["error"])[:120]
        elif fa.get("suspect"):
            extras["fleet_auto_error"] = (
                "no trustworthy device numbers for the fleet-auto lane "
                "(cpu fallback); see bench_partial.json")
        else:
            for key in ("autoscale_converge_s", "fleet_scaledown_shed_frac",
                        "canary_rollback", "fleet_models_served"):
                if fa.get(key) is not None:
                    extras[key] = fa[key]
        for key in ("canary_promoted", "fleet_session_failures",
                    # pva-tpu-hbm verdicts ride regardless too: a refused
                    # round must still say whether the burn-rate rule
                    # flapped and whether measured admission held
                    "alert_false_positives", "budget_lies_refused"):
            if fa.get(key) is not None:
                extras[key] = fa[key]
        flush_partial()

    if args.stream:
        # STREAM lane: child-isolated like the fleet lane (a wedged
        # compile loses the lane, not the round). The refusal rule
        # mirrors fleet/dataplane: a failed, parity-broken, or
        # cpu-fallback lane headlines stream_error INSTEAD of the
        # numbers; the verdict keys (parity/recompiles) ride regardless.
        st = run_child("__stream__", args, user_smoke or not device_ok,
                       _model_timeout(args))
        extras["stream"] = st  # full record -> bench_partial.json
        if "error" in st:
            extras["stream_error"] = str(st["error"])[:120]
        elif st.get("suspect"):
            extras["stream_error"] = (
                "no trustworthy device numbers for the stream lane "
                "(cpu fallback); see bench_partial.json")
        elif not st.get("stream_parity"):
            extras["stream_error"] = (
                "incremental advance logits diverged from the full-clip "
                "recompute (see bench_partial.json stream record)")
        else:
            for key in ("stream_incremental_speedup",
                        "stream_h2d_bytes_frac", "stream_p99_ms",
                        "stream_trunk_speedup", "stream_trunk_top1_delta"):
                if st.get(key) is not None:
                    extras[key] = st[key]
            if st.get("stream_trunk_refused"):
                # quality-gate refusal: the top-1 delta headlines (just
                # above) but the speedup does not — the refusal reason
                # rides so the round is self-explaining
                extras["stream_trunk_error"] = str(
                    st["stream_trunk_refused"])[:120]
        for key in ("stream_parity", "stream_recompiles",
                    "stream_trunk_parity", "stream_trunk_recompiles"):
            if st.get(key) is not None:
                extras[key] = st[key]
        flush_partial()

    if args.kbench:
        # kernel-microbench lane: child-isolated like the model benches,
        # and under the same dead-tunnel rule — a non-smoke child touches
        # the real backend, which wedges at init when the tunnel is down,
        # so the lane falls back to the CPU-pinned smoke child there. The
        # speedups are same-backend ratios, honest on whatever backend the
        # child lands on (platform-labeled; only a TPU run is a device
        # claim, and raw ms never leave bench_partial.json)
        kb = run_child("__kbench__", args, user_smoke or not device_ok,
                       _model_timeout(args))
        extras["kbench"] = kb  # full record -> bench_partial.json
        if "error" in kb:
            extras["kbench_error"] = str(kb["error"])[:120]
        elif not kb.get("parity_ok", False):
            # a fused kernel that diverged from its reference must
            # headline the violation INSTEAD of any speedup
            extras["kbench_error"] = ("kernel parity violation (see "
                                      "bench_partial.json kbench record)")
        else:
            from pytorchvideo_accelerate_tpu.ops.kbench import (
                headline_keys,
            )

            extras.update(headline_keys(kb))
        flush_partial()

    if args.serve_smoke:
        # serving lane runs in the parent (CPU-pinned, tiny model) but
        # bounded like the host benches: a wedged compile or stuck batcher
        # thread must not break the one-JSON-line contract
        from concurrent.futures import ThreadPoolExecutor as _TPE
        from concurrent.futures import TimeoutError as _FutTimeout

        _pool = _TPE(max_workers=1)
        try:
            extras["serving"] = _pool.submit(
                bench_serving, args).result(timeout=600)
        except _FutTimeout:
            log("[serving] timed out after 600s")
            extras["serving"] = {"error": "timeout after 600s"}
        except Exception as e:
            log(f"[serving] FAILED: {type(e).__name__}: {e}")
            extras["serving"] = {"error": f"{type(e).__name__}: {e}"}
        _pool.shutdown(wait=False)
        flush_partial()

    headline = finalize(results, extras, user_smoke)
    if user_smoke and args.trainer:
        # CI contract (same spirit as the serving lane below): the obs
        # step-time breakdown must come out of the trainer lane. Asserted
        # on extras, not the headline — finalize() may legitimately shed
        # these keys to fit the driver's line budget, and a successful run
        # must not fail over size shedding (test_bench_contract covers the
        # passthrough itself).
        for key in ("obs_step_s", "obs_input_wait_frac", "obs_h2d_s",
                    "train_recompiles"):
            assert key in extras, (
                f"trainer smoke ran but produced no {key!r}: "
                f"{extras.get('trainer_error') or sorted(extras)}")
        # honest-MFU contract (ROADMAP item 1): the trainer lane must
        # headline a NON-NULL mfu_analytic with its provenance label even
        # on CPU smoke — the analytic FLOPs counter traces everywhere and
        # utils/hw.resolve_peak calibrates a measured denominator where
        # no datasheet peak exists. A null here means the honest-MFU
        # plumbing silently fell out of fit().
        assert extras.get("mfu_analytic") is not None, (
            f"trainer smoke produced no mfu_analytic: "
            f"{extras.get('trainer_error') or sorted(extras)}")
        assert extras.get("mfu_source") in ("costmodel", "analytic"), (
            f"mfu_analytic lacks a provenance label: "
            f"{extras.get('mfu_source')!r}")
        # steady-state-zero recompile contract: after the first step's
        # legitimate compile, the train step's jit cache must not grow
        # (pva_train_recompiles gauge; the recompile rule's runtime
        # teeth). None = probe unavailable on this jax — degrade to
        # "unknown" rather than failing the bench over a missing API.
        assert extras["train_recompiles"] in (0, None), (
            f"steady-state recompiles detected: {extras['train_recompiles']} "
            "jit cache entries compiled after warmup (see "
            "docs/STATIC_ANALYSIS.md, rule `recompile`)")
        # self-healing contract (docs/RELIABILITY.md § divergence
        # runbook): the guard runs ARMED in the trainer lane; on a clean
        # synthetic run it must report zero rollbacks and zero
        # quarantined clips — anything else is a guard false positive
        for key in ("guard_rollbacks", "quarantined_clips"):
            assert key in extras, (
                f"trainer smoke ran with the guard armed but produced no "
                f"{key!r}: "
                f"{extras.get('trainer_error') or sorted(extras)}")
            assert extras[key] == 0, (
                f"guard reported {key}={extras[key]} on a clean smoke "
                "run (false positive; see docs/RELIABILITY.md)")
        # memory-ledger contract (pva-tpu-hbm, docs/OBSERVABILITY.md §
        # memory ledger): the hbm triple must come out of the trainer
        # lane, and on the forced-host smoke child (CPU pinned, no
        # backend memory_stats) the source MUST read "estimate" — a
        # "measured" label here would mean the ledger fabricated device
        # bytes, the exact lie the ledger exists to prevent
        for key in ("hbm_peak_bytes", "hbm_attributed_frac", "hbm_source"):
            assert extras.get(key) is not None, (
                f"trainer smoke ran but produced no {key!r}: "
                f"{extras.get('trainer_error') or sorted(extras)}")
        assert extras["hbm_source"] == "estimate", (
            f"CPU smoke host reported hbm_source="
            f"{extras['hbm_source']!r} — estimate-only hosts must never "
            "claim measured device bytes")
    if user_smoke:
        # dynamic-sanitizer contract, the third leg alongside lint-clean
        # and train_recompiles == 0: the bundled pva-tpu-tsan stress pass
        # over the threaded layers must report zero races / lock cycles
        # (docs/STATIC_ANALYSIS.md § dynamic sanitizer)
        assert extras.get("tsan_findings") == 0, (
            f"pva-tpu-tsan found {extras.get('tsan_findings')} race/"
            "lock-cycle finding(s) on the stress scenario (report logged "
            "above; see docs/STATIC_ANALYSIS.md)")
        # resilience contract, fourth leg: the chaos scenario already
        # gated at the top; the headline must carry its verdict too
        assert extras.get("chaos_findings") == 0, (
            f"pva-tpu-chaos found {extras.get('chaos_findings')} "
            "unrecovered fault(s) (see docs/RELIABILITY.md)")
        # compiled-graph contract, fifth leg: graphcheck already gated at
        # the top; the headline must carry its verdict too
        assert extras.get("graphcheck_findings") == 0, (
            f"pva-tpu-graphcheck found {extras.get('graphcheck_findings')} "
            "finding(s) (see docs/STATIC_ANALYSIS.md)")
        # collective-schedule contract: spmdcheck already gated at the
        # top; the headline must carry its verdict too
        assert extras.get("spmdcheck_findings") == 0, (
            f"pva-tpu-spmdcheck found {extras.get('spmdcheck_findings')} "
            "finding(s) (see docs/STATIC_ANALYSIS.md § spmdcheck)")
    if user_smoke and args.multichip:
        # 2-D-mesh contract (docs/PARALLELISM.md): the scaling lane must
        # produce its parity verdict and curve, parity must HOLD, and the
        # steady-state-zero recompile contract must survive the (data,
        # model) layout — not just the 1-D DP path the trainer lane runs
        for key in ("mesh_parity", "multichip_cps_per_chip"):
            assert key in extras, (
                f"multichip smoke ran but produced no {key!r}: "
                f"{extras.get('multichip_error') or sorted(extras)}")
        assert extras["mesh_parity"] is True, (
            "N-device (data, model) mesh diverged from the 1-device loss "
            f"trajectory: {extras.get('multichip')}")
        assert extras.get("mesh_ckpt_portable") in (True, None), (
            f"mesh-reshape checkpoint restore failed: "
            f"{extras.get('multichip')}")
        assert extras.get("multichip_train_recompiles") in (0, None), (
            "steady-state recompiles under the 2-D mesh layout: "
            f"{extras.get('multichip_train_recompiles')}")
        # collective-schedule contract (docs/STATIC_ANALYSIS.md
        # § spmdcheck): the lane's emulated-host probe must replay an
        # identical schedule on every host (zero divergence), and the
        # seeded-divergence leg must PROVE the differ catches a real
        # skew — a clean report from a blind recorder gates nothing
        assert extras.get("spmd_schedule_divergence") == 0, (
            "MULTICHIP collective schedules diverged across emulated "
            f"hosts: {extras.get('spmd_schedule_divergence')} "
            f"({extras.get('multichip')})")
        assert extras.get("spmd_divergence_detected") is True, (
            "seeded schedule divergence was NOT detected by the "
            "recorder/differ — the divergence gate is blind "
            f"({extras.get('multichip')})")
    if user_smoke and args.pipeline:
        # PIPELINE acceptance (docs/PARALLELISM.md § pipeline): the P=2/4
        # stage pipelines hold the P=1 fp32 loss trajectory at identical
        # steps, the bubble fraction is headlined (analytic AND measured),
        # donation survives the stage scan, and the steady-state-zero
        # recompile contract holds under the pipelined layout
        pc = extras.get("pipeline", {})
        assert "pipeline_error" not in extras, (
            f"PIPELINE lane failed: {extras['pipeline_error']}: {pc}")
        assert extras.get("pipeline_parity") is True, (
            "pipelined VideoMAE pretrain diverged from the P=1 loss "
            f"trajectory: {pc}")
        for key in ("pipeline_cps_per_chip", "pipeline_bubble_frac",
                    "pipeline_bubble_frac_analytic"):
            assert extras.get(key) is not None, (
                f"pipeline smoke ran but produced no {key!r}: {pc}")
        assert extras.get("pipeline_donation_verified") is True, (
            f"pipelined step donation not verified by graphcheck: {pc}")
        assert extras.get("pipeline_train_recompiles") in (0, None), (
            "steady-state recompiles under the pipelined layout: "
            f"{extras.get('pipeline_train_recompiles')}")
    if user_smoke and args.serve_smoke:
        # smoke mode doubles as the CI check that the serving lane's
        # headline keys didn't silently fall out (same contract as the
        # trainer lane's input_wait_frac assert)
        for key in ("serve_p50_ms", "serve_p99_ms", "serve_fill_ratio"):
            assert key in headline, (
                f"serving smoke ran but headline misses {key!r}: "
                f"{extras.get('serving')}")
    if user_smoke and args.kbench:
        # kernel-lane acceptance (docs/KERNELS.md): every fused kernel
        # holds parity with its XLA reference (benched shape AND
        # interpret-mode Pallas), every per-kernel speedup key made the
        # headline, and at least one fused kernel shows a real win over
        # the reference on this host — the folded depthwise beats XLA's
        # grouped conv by orders of magnitude even on the CPU smoke host
        kb = extras.get("kbench", {})
        assert "kbench_error" not in extras, (
            f"kbench lane failed: {extras['kbench_error']}: {kb}")
        assert extras.get("kbench_parity_ok") is True, (
            f"kbench parity keys missing/false: {kb}")
        for name in kb.get("kernels", {}):
            assert f"kbench_{name}_speedup" in extras, (
                f"kbench ran but headline misses kbench_{name}_speedup")
        assert kb.get("best_speedup", 0) >= 1.15, (
            "no fused kernel beat its XLA reference by >=1.15x on the "
            f"smoke host: {kb}")
    if user_smoke and args.fleet:
        # SERVE_FLEET acceptance (docs/SERVING.md § fleet): the open-loop
        # harness sustained its arrival rate against >=2 replicas, p99
        # held the configured SLO, the mid-load hot-swap completed with a
        # measured blackout, and NOTHING failed non-shed — sheds are the
        # admission/deadline machinery working, failures are bugs
        fl = extras.get("fleet", {})
        assert "fleet_error" not in extras, (
            f"SERVE_FLEET lane failed: {extras['fleet_error']}: {fl}")
        for key in ("serve_rps", "serve_p99_ms_under_load",
                    "swap_blackout_ms", "fleet_shed_frac"):
            assert extras.get(key) is not None, (
                f"fleet smoke ran but produced no {key!r}: {fl}")
        assert fl.get("replicas", 0) >= 2, f"fleet ran <2 replicas: {fl}"
        assert fl.get("open_loop_ok") is True, (
            f"loadgen degraded toward closed-loop (schedule slipped): {fl}")
        assert fl.get("fleet_failed") == 0, (
            f"fleet load run had non-shed failures: {fl}")
        assert fl.get("weights_cut_over") is True, (
            f"mid-load hot-swap did not change served weights: {fl}")
        assert extras["serve_p99_ms_under_load"] <= fl.get(
            "slo_p99_ms", float("inf")), (
            f"serve_p99_ms_under_load {extras['serve_p99_ms_under_load']} "
            f"ms breaches the {fl.get('slo_p99_ms')} ms SLO: {fl}")
        # distributed-tracing acceptance (docs/OBSERVABILITY.md § tracing):
        # the lane ran traced, at least one request was head-sampled, the
        # merged multi-process timeline links router->replica->engine
        # across the process boundary, and the tracer's self-measured
        # bookkeeping stayed under 2% of the run's wall time
        assert fl.get("trace_sampled", 0) >= 1, (
            f"fleet lane sampled no traces: {fl}")
        assert fl.get("trace_head_sampled", 0) >= 1, (
            "head-based sampling produced no traces (only forced probes "
            f"recorded — the obs.trace_sample_rate path is broken): {fl}")
        assert fl.get("trace_linked") is True, (
            "no sampled request spans router->replica->engine across "
            f"processes in the merged trace: {fl}")
        overhead = fl.get("trace_overhead_frac")
        assert overhead is not None and overhead < 0.02, (
            f"tracing overhead {overhead} is not under 2% of run wall "
            f"time: {fl}")
    if user_smoke and args.fleet_auto:
        # FLEET_AUTO acceptance (docs/SERVING.md § fleet intelligence):
        # the autoscaler CONVERGED on the traffic step — it grew the
        # fleet, the last scaling action landed within the deadline, and
        # a steady probe at the full stepped rate held the p99 SLO; the
        # scale-down drained a victim without losing a single live
        # streaming session; the seeded-regression canary auto-rolled-
        # back (blues restored) while the clean artifact promoted; and
        # >=2 model families served off one pool with the over-budget
        # family shed at the door
        fa = extras.get("fleet_auto", {})
        assert "fleet_auto_error" not in extras, (
            f"FLEET_AUTO lane failed: {extras['fleet_auto_error']}: {fa}")
        for key in ("autoscale_converge_s", "fleet_scaledown_shed_frac",
                    "canary_rollback", "fleet_models_served"):
            assert extras.get(key) is not None, (
                f"fleet-auto smoke ran but produced no {key!r}: {fa}")
        assert fa.get("autoscale_converged") is True, (
            f"autoscaler did not converge on the traffic step: {fa}")
        assert extras["autoscale_converge_s"] <= fa.get(
            "converge_deadline_s", float("inf")), (
            f"autoscaler converged too slowly: {fa}")
        assert fa.get("scaled_up_to", 0) > fa.get("replicas_start", 99), (
            f"traffic step did not grow the fleet: {fa}")
        assert fa.get("open_loop_ok") is True, (
            f"fleet-auto loadgen degraded toward closed-loop: {fa}")
        assert extras.get("fleet_session_failures") == 0, (
            f"scale-down lost live streaming session work: {fa}")
        assert fa.get("fleet_sessions_rehomed", 0) >= 1, (
            f"scale-down drained no session-carrying replica: {fa}")
        assert extras.get("canary_rollback") == 1, (
            f"seeded-regression canary did not auto-rollback: {fa}")
        assert fa.get("canary_blue_restored") is True, (
            f"rollback did not restore the blue engines: {fa}")
        assert extras.get("canary_promoted") is True, (
            f"clean canary was not promoted fleet-wide: {fa}")
        assert extras.get("fleet_models_served", 0) >= 2, (
            f"fewer than 2 model families served off the pool: {fa}")
        assert fa.get("budget_shed_ok") is True, (
            "over-budget family did not shed (or the in-budget family "
            f"stopped serving): {fa}")
        # pva-tpu-hbm acceptance (docs/OBSERVABILITY.md § burn-rate
        # alerts): the seeded SLO breach fired its multi-window rule
        # EXACTLY once and cleared on recovery — zero calm-phase fires,
        # zero flap re-fires — and the budget-lies probe proved the
        # admission flip: the under-declaring family the declared
        # estimate admitted is refused where the ledger measures it
        assert extras.get("alert_false_positives") == 0, (
            f"burn-rate rule fired outside the seeded breach: {fa}")
        assert fa.get("alert_fired_once") is True, (
            f"seeded SLO breach did not fire exactly one alert: {fa}")
        assert fa.get("alert_cleared") is True, (
            f"burn-rate alert did not clear on recovery: {fa}")
        assert extras.get("budget_lies_refused") is True, (
            "measured-byte admission did not refuse the under-declaring "
            f"family the declared estimate admitted: {fa}")
    if user_smoke and args.stream:
        # STREAM acceptance (docs/SERVING.md § streaming): incremental
        # advance logits matched the full-clip recompute every measured
        # round, the incremental path is >= 1.5x cheaper per label at
        # stride <= T/4 with the per-advance H2D payload cut >= 4x,
        # steady-state streaming compiled NOTHING after warmup, and the
        # open-loop stream load finished with zero non-shed failures
        # under its label-latency SLO
        st = extras.get("stream", {})
        assert "stream_error" not in extras, (
            f"STREAM lane failed: {extras['stream_error']}: {st}")
        assert extras.get("stream_parity") is True, (
            f"incremental/full-recompute parity gate failed: {st}")
        assert extras.get("stream_recompiles") == 0, (
            "steady-state session advances recompiled "
            f"{extras.get('stream_recompiles')} stream step(s) after "
            f"warmup: {st}")
        for key in ("stream_incremental_speedup", "stream_h2d_bytes_frac",
                    "stream_p99_ms"):
            assert extras.get(key) is not None, (
                f"stream smoke ran but produced no {key!r}: {st}")
        assert st.get("stride", 1) * 4 <= st.get("window", 0), (
            f"stream lane ran at stride > window/4: {st}")
        assert extras["stream_incremental_speedup"] >= 1.5, (
            f"incremental advance is not >=1.5x cheaper per label: {st}")
        assert extras["stream_h2d_bytes_frac"] <= 0.25, (
            f"per-advance H2D payload not cut >=4x: {st}")
        assert st.get("stream_failed") == 0, (
            f"stream load run had non-shed failures: {st}")
        assert st.get("open_loop_ok") is True, (
            f"stream loadgen degraded toward closed-loop: {st}")
        assert extras["stream_p99_ms"] <= st.get(
            "slo_label_p99_ms", float("inf")), (
            f"stream_p99_ms {extras['stream_p99_ms']} breaches the "
            f"{st.get('slo_label_p99_ms')} ms label SLO: {st}")
        # trunk-reuse acceptance (docs/SERVING.md § trunk-reuse): the
        # KV-ring advance matched the full-recompute-under-the-same-mask
        # replay, compiled nothing after warmup, cleared the evaluate()
        # top-1 gate vs the bidirectional baseline, and is >= 2x cheaper
        # per label decode-inclusive than re-running the masked trunk
        assert extras.get("stream_trunk_parity") is True, (
            f"KV-trunk parity vs the same-mask replay failed: {st}")
        assert extras.get("stream_trunk_recompiles") == 0, (
            "steady-state KV-trunk advances recompiled "
            f"{extras.get('stream_trunk_recompiles')} step(s) after "
            f"warmup: {st}")
        assert "stream_trunk_error" not in extras, (
            f"trunk quality gate refused the speedup: "
            f"{extras['stream_trunk_error']}: {st}")
        delta = extras.get("stream_trunk_top1_delta")
        assert delta is not None and delta <= st.get(
            "stream_trunk_top1_tol", 0.0), (
            f"trunk top-1 delta {delta} breaches the quality gate: {st}")
        assert extras.get("stream_trunk_speedup", 0.0) >= 2.0, (
            "KV-ring trunk advance is not >=2x cheaper per label "
            f"(decode-inclusive): {st}")
        # memory-ledger contract (pva-tpu-hbm): the streaming ring pools
        # + weight pins registered with the armed ledger, so the lane's
        # record must carry a non-trivial attribution with the honest
        # provenance label (estimate on the CPU-pinned smoke child)
        assert st.get("hbm_attributed_frac") is not None, (
            f"stream smoke ran but produced no hbm_attributed_frac: {st}")
        assert st.get("hbm_source") == "estimate", (
            f"CPU smoke stream lane reported hbm_source="
            f"{st.get('hbm_source')!r} — estimate-only hosts must never "
            "claim measured device bytes")
        assert st.get("hbm_peak_bytes", 0) > 0, (
            "stream lane attributed zero peak bytes with ring pools and "
            f"weight pins armed — ledger registration fell out: {st}")
    if user_smoke and args.dataplane:
        # DATA_PLANE acceptance (docs/INPUT_PIPELINE.md § disaggregated
        # data plane): N>=2 remote decode workers produced a byte-
        # identical batch stream to the local loader on the same source/
        # seed, and the remote input-wait fraction is no worse than the
        # local loader's on this host — decode scale-out must never cost
        # the trainer wait time, or the whole lever is fake
        dpl = extras.get("dataplane", {})
        assert "dataplane_error" not in extras, (
            f"DATA_PLANE lane failed: {extras['dataplane_error']}: {dpl}")
        assert dpl.get("parity") is True, (
            f"remote batch stream diverged from the local loader: {dpl}")
        assert extras.get("dataplane_workers", 0) >= 2, (
            f"dataplane lane ran <2 remote workers: {dpl}")
        for key in ("dataplane_cps", "dataplane_input_wait_frac"):
            assert extras.get(key) is not None, (
                f"dataplane smoke ran but produced no {key!r}: {dpl}")
        from pytorchvideo_accelerate_tpu.dataplane.bench import (
            WAIT_FRAC_TOLERANCE,
        )

        assert (extras["dataplane_input_wait_frac"]
                <= dpl["local_input_wait_frac"] + WAIT_FRAC_TOLERANCE), (
            f"remote input_wait_frac {extras['dataplane_input_wait_frac']} "
            f"worse than local {dpl['local_input_wait_frac']}: {dpl}")
    extras["headline"] = headline  # full record keeps the compact line too
    flush_partial()
    print(json.dumps(headline))
    sys.stdout.flush()
    sys.stderr.flush()
    # hard exit: stuck host-bench threads or lingering forked loader workers
    # must not keep the process alive after the JSON line is out
    os._exit(0)


def feed_projection(dp: dict) -> dict:
    """The design consequence of the measured host-feed rates (VERDICT r4
    weak 3): at plausible DEVICE training rates, how many decode workers /
    host cores must feed ONE chip, on the live-decode path vs the
    pre-decoded cache path?

    Projected from this host's measured per-core loader throughput, not a
    guess. Workers and cores are different resources: the per-worker rate
    reflects GIL/core sharing at the measured worker:core ratio, while the
    per-core rate assumes each core saturated — cores are the buyable
    unit. The conclusion: live cv2 decode at reference geometry costs
    multiple host cores per chip (scaling linearly with device rate)
    where the cache read path costs well under one, so the pre-decoded
    frame cache (data/cache.py) is MANDATORY at scale, not an
    optimization. The cache-path number carries its own caveat: measured
    on a page-cache-resident fixture, so it bounds CPU cost only, not
    cold-storage bandwidth."""
    cores = os.cpu_count() or 1
    loader_cps = dp["loader_thread_clips_per_sec"]
    cores_used = min(dp["num_workers"], cores)  # thread workers share cores
    loader_cps_per_core = loader_cps / cores_used
    cache_cps = dp.get("cache_clips_per_sec")
    # cache bench runs 2 reader threads (cache.bench_decode_vs_cache)
    cache_cps_per_core = cache_cps / min(2, cores) if cache_cps else None
    # storage-bound companion (pread over an evicted page cache)
    cold_cps = dp.get("cache_cold_clips_per_sec")
    # u8-through loader (host_cast=u8): no normalize + quarter-size
    # batching (measured ratio lives in the data block / docs/PERF.md)
    u8_cps = dp.get("loader_thread_u8_clips_per_sec")
    u8_per_core = (u8_cps / cores_used) if u8_cps else None
    per_worker = loader_cps / dp["num_workers"]
    rows = []
    for rate in (100, 200, 400):
        row = {"device_clips_per_sec": rate,
               "decode_workers_per_chip": math.ceil(rate / per_worker),
               "decode_cores_per_chip": round(rate / loader_cps_per_core, 1)}
        if u8_per_core:
            row["decode_u8_cores_per_chip"] = round(rate / u8_per_core, 1)
        if cache_cps_per_core:
            row["cache_cores_per_chip"] = round(rate / cache_cps_per_core, 2)
        if cold_cps:
            # storage, not CPU: fraction of one cold-read stream's
            # bandwidth a chip's appetite consumes
            row["cache_cold_streams_per_chip"] = round(rate / cold_cps, 2)
        rows.append(row)
    out = {
        "basis": {"loader_clips_per_sec_per_core":
                  round(loader_cps_per_core, 2),
                  "loader_u8_clips_per_sec_per_core":
                  round(u8_per_core, 2) if u8_per_core else None,
                  "measured_on_cores": cores,
                  "cache_is_page_cache_resident": True,
                  "cache_cold_clips_per_sec": cold_cps,
                  "cache_cold_mb_per_sec": dp.get("cache_cold_mb_per_sec")},
        "rows": rows,
        "conclusion": ("live decode costs multiple host cores per chip, "
                       "linear in device rate; the cache path costs <0.1 — "
                       "pre-decoded cache (data/cache.py build + ClipLoader "
                       "cache path) is mandatory at scale"),
    }
    return out


# The driver captures only the trailing ~2000 bytes of stdout; a headline
# line longer than that arrives truncated mid-line and parses as null
# (BENCH_r04 casualty). Hard budget with headroom; enforced in finalize()
# and locked by tests/test_bench_contract.py.
MAX_LINE_BYTES = 1500


def finalize(results: dict, extras: dict, user_smoke: bool) -> dict:
    """Assemble the single compact JSON line from per-model results + extras.

    The line carries headline numbers only (metric/value/mfu/suspect/error,
    one scalar per model, probe counts); everything else — full per-model
    dicts, probe timestamps, data-pipeline and transport blocks — lives in
    bench_partial.json, which main() flushes throughout the run."""
    flag_name = "slowfast_r50"
    flag = results.get(flag_name, {})
    if "clips_per_sec_per_chip" not in flag:  # flagship failed: next best
        flag_name, flag = next(
            ((n, r) for n, r in results.items()
             if "clips_per_sec_per_chip" in r), ("none", {}))

    baseline = None
    try:
        published = json.load(
            open(os.path.join(HERE, "BASELINE.json"))).get("published", {})
        baseline = published.get("clips_per_sec_per_chip")
    except Exception:
        pass
    value = flag.get("clips_per_sec_per_chip", 0.0)
    vs = value / baseline if baseline else 1.0

    smoke_tag = ", smoke" if flag.get("smoke") else ""
    out = {
        "metric": f"train clips/sec/chip ({flag_name}, "
                  f"{flag.get('frames', '?')}f, {flag.get('crop', '?')}px, "
                  f"bf16{smoke_tag})",
        "value": value,
        "unit": "clips/sec/chip",
        "vs_baseline": round(vs, 3),
        "step_ms_blocked": flag.get("step_ms_blocked"),
        "tflops_per_sec": flag.get("tflops_per_sec_per_chip"),
        "mfu": flag.get("mfu"),
        "suspect": flag.get("suspect"),
        # one scalar per model: clips/s/chip, or its error head
        "models": {
            n: (r["clips_per_sec_per_chip"]
                if "clips_per_sec_per_chip" in r
                else "err: " + str(r.get("error", "?"))[:40])
            for n, r in results.items() if not n.endswith("__device_error")
            and not n.endswith("__smoke_fallback")
        },
        "detail": "bench_partial.json",
    }
    # a multichip lane that refused its numbers (cpu fallback) headlines
    # the refusal INSTEAD of the perf keys — verdicts (parity/portability/
    # recompiles) still ride; error strings truncate on entry
    mc_perf = ("multichip_cps_per_chip", "multichip_forced_host",
               "multichip_mfu", "multichip_mfu_analytic",
               "multichip_mfu_peak_source")
    # fleet-lane perf keys obey the same refusal rule: a fleet_error (cpu
    # fallback or a failed lane) headlines INSTEAD of the numbers; the
    # trace verdicts (sampled count + tracer overhead fraction) ride with
    # them — they come from the same lane and are meaningless without it
    fleet_perf = ("serve_rps", "serve_p99_ms_under_load",
                  "swap_blackout_ms", "fleet_shed_frac",
                  "trace_sampled", "trace_overhead_frac")
    # FLEET_AUTO control-loop perf keys under the same refusal rule: a
    # fleet_auto_error headlines INSTEAD of the numbers; the verdicts
    # (canary_promoted / fleet_session_failures) ride regardless
    fleet_auto_perf = ("autoscale_converge_s", "fleet_scaledown_shed_frac",
                       "canary_rollback", "fleet_models_served")
    # DATA_PLANE lane perf keys under the same refusal rule: a
    # dataplane_error (failed lane or broken byte parity) headlines
    # INSTEAD of the numbers
    dataplane_perf = ("dataplane_cps", "dataplane_input_wait_frac",
                      "dataplane_workers")
    # PIPELINE lane perf keys under the same refusal rule; the parity /
    # donation / recompile verdicts ride regardless
    pipeline_perf = ("pipeline_cps_per_chip", "pipeline_bubble_frac",
                     "pipeline_bubble_frac_analytic", "pipeline_stages")
    # STREAM lane perf keys under the same refusal rule: a stream_error
    # (failed lane, broken parity, cpu fallback) headlines INSTEAD of the
    # numbers; the parity/recompile verdicts ride regardless. The trunk
    # sub-lane's top-1 delta counts as a perf key here on purpose: it is
    # a measured eval number, meaningless on a refused round
    stream_perf = ("stream_incremental_speedup", "stream_h2d_bytes_frac",
                   "stream_p99_ms", "stream_trunk_speedup",
                   "stream_trunk_top1_delta")
    for key in ("trainer_vs_rawstep", "trainer_cps_chip", "trainer_mfu",
                "mfu_analytic", "mfu_source", "mfu_peak_source",
                "trainer_input_wait_frac", "obs_step_s",
                "obs_input_wait_frac", "obs_h2d_s", "train_recompiles",
                "guard_rollbacks", "quarantined_clips",
                "tsan_findings", "chaos_findings", "graphcheck_findings",
                "spmdcheck_findings",
                "mesh_parity",
                "mesh_ckpt_portable", "multichip_train_recompiles",
                "spmd_schedule_divergence", "spmd_divergence_detected",
                "pipeline_parity", "pipeline_donation_verified",
                "pipeline_train_recompiles",
                "stream_parity", "stream_recompiles",
                "stream_trunk_parity", "stream_trunk_recompiles",
                "canary_promoted", "fleet_session_failures",
                # pva-tpu-hbm: the ledger triple (trainer lane) + the
                # burn-rate/admission verdicts (fleet_auto lane) —
                # hbm_source is the provenance label that keeps an
                # "estimate" peak from ever reading as a device claim
                "hbm_peak_bytes", "hbm_attributed_frac", "hbm_source",
                "alert_false_positives", "budget_lies_refused",
                *mc_perf, *fleet_perf, *fleet_auto_perf, *dataplane_perf,
                *pipeline_perf, *stream_perf):
        if key in extras and not (
                (key in mc_perf and "multichip_error" in extras)
                or (key in fleet_perf and "fleet_error" in extras)
                or (key in fleet_auto_perf
                    and "fleet_auto_error" in extras)
                or (key in dataplane_perf and "dataplane_error" in extras)
                or (key in pipeline_perf and "pipeline_error" in extras)
                or (key in stream_perf and "stream_error" in extras)):
            out[key] = extras[key]
    if "stream_error" in extras:
        out["stream_error"] = str(extras["stream_error"])[:120]
    if "stream_trunk_error" in extras:
        out["stream_trunk_error"] = str(extras["stream_trunk_error"])[:120]
    if "pipeline_error" in extras:
        out["pipeline_error"] = str(extras["pipeline_error"])[:120]
    if "multichip_error" in extras:
        out["multichip_error"] = str(extras["multichip_error"])[:120]
    if "fleet_error" in extras:
        out["fleet_error"] = str(extras["fleet_error"])[:120]
    if "fleet_auto_error" in extras:
        out["fleet_auto_error"] = str(extras["fleet_auto_error"])[:120]
    if "dataplane_error" in extras:
        out["dataplane_error"] = str(extras["dataplane_error"])[:120]
    # kernel-microbench keys (pva-tpu-kbench): dimensionless same-backend
    # speedup ratios + platform label (never raw ms — those live in
    # bench_partial.json); a failed or parity-broken lane headlined
    # kbench_error INSTEAD of speedups at the lane site above
    for key in sorted(extras):
        if key.startswith("kbench_"):
            out[key] = extras[key]
    # serving lane: request-latency percentiles + batcher fill ratio
    serving = extras.get("serving", {})
    if "error" in serving:
        out["serve_error"] = str(serving["error"])[:120]
    else:
        for key in ("serve_p50_ms", "serve_p99_ms", "serve_fill_ratio"):
            if key in serving:
                out[key] = serving[key]
    # error strings can be whole tracebacks: truncate on entry, every one
    if "trainer_error" in extras:
        out["trainer_error"] = str(extras["trainer_error"])[:200]
    if "error" in extras:
        out["error"] = str(extras["error"])[:280]
    # probe evidence arrives as counts; timestamps live in bench_partial.json
    # and .probe_log.jsonl (the whole-round log, manual + bench probes)
    probes = list(extras.get("probe_attempts", []))
    try:
        with open(os.path.join(HERE, ".probe_log.jsonl")) as f:
            round_log = [json.loads(ln)
                         for ln in f.read().strip().splitlines() if ln]
    except (OSError, ValueError):
        round_log = []
    if probes or round_log:
        src = round_log or probes
        out["probes"] = {"run": len(probes),
                         "round": len(round_log),
                         "ok": sum(1 for p in src if p.get("ok")),
                         "last": src[-1].get("ts")}
    # missing platform covers error-only and empty flagship results too:
    # the driver must never read a silent zero as a real measurement
    if flag.get("platform", "cpu") == "cpu" and not user_smoke:
        out["suspect"] = True
        out["error"] = ("no trustworthy device number for the flagship "
                        "(unreachable tunnel or failed bench; see "
                        "bench_partial.json + .probe_log.jsonl); CPU/smoke "
                        "values are not device numbers")
    if out.get("suspect"):
        # refusal rule for the flagship's own device-shaped perf keys: a
        # suspect round was headlining a literal `"tflops_per_sec": 0.0`
        # (BENCH_r05) — a zero pva-tpu-perfdiff could one day diff against
        # a real device number. Shed them like the lane perf keys above;
        # `value` stays (its metric string carries the smoke tag and the
        # suspect flag rides beside it, and perfdiff refuses suspect
        # rounds wholesale).
        out.pop("tflops_per_sec", None)
        out.pop("step_ms_blocked", None)
    # hard size guarantee: shed optional detail one key at a time before
    # ever exceeding the driver's capture window; the per-model map and
    # the truncations are LAST resorts (dropping a lane's optional extras
    # must never cost the models summary)
    for k in ("probes", "trace_overhead_frac", "trace_sampled",
              "multichip_mfu_peak_source", "multichip_mfu_analytic",
              "multichip_mfu", "multichip_forced_host",
              "multichip_train_recompiles", "multichip_error",
              "multichip_cps_per_chip",
              # spmd schedule verdicts shed just before the mesh verdicts
              # (the divergence gate is this arc's acceptance metric)
              "spmd_divergence_detected", "spmd_schedule_divergence",
              "mesh_ckpt_portable", "mesh_parity",
              # the PIPELINE lane sheds after the multichip curve (its
              # bubble-frac headline is this arc's acceptance metric) but
              # before the fleet/dataplane/kbench groups
              "pipeline_error", "pipeline_train_recompiles",
              "pipeline_donation_verified", "pipeline_stages",
              "pipeline_bubble_frac_analytic", "pipeline_parity",
              "pipeline_bubble_frac", "pipeline_cps_per_chip",
              "fleet_error", "fleet_shed_frac", "swap_blackout_ms",
              "serve_p99_ms_under_load", "serve_rps",
              # the FLEET_AUTO control lane sheds after the fleet group
              # (convergence is this arc's acceptance metric, so it goes
              # last of the group); verdicts shed before perf keys
              "fleet_auto_error", "canary_promoted",
              "fleet_session_failures", "budget_lies_refused",
              "alert_false_positives", "fleet_models_served",
              "fleet_scaledown_shed_frac", "canary_rollback",
              "autoscale_converge_s",
              # the STREAM lane sheds after the fleet group but before
              # dataplane/kbench (its speedup is this arc's headline);
              # the trunk SPEEDUP sheds before its top-1 delta on purpose
              # — a speedup must never outlive its quality verdict
              "stream_trunk_error", "stream_error", "stream_recompiles",
              "stream_parity", "stream_trunk_recompiles",
              "stream_trunk_parity",
              "stream_p99_ms", "stream_h2d_bytes_frac",
              "stream_trunk_speedup", "stream_trunk_top1_delta",
              "stream_incremental_speedup",
              "dataplane_error", "dataplane_workers",
              "dataplane_input_wait_frac", "dataplane_cps",
              "kbench_conv311_sf_res4_speedup",
              "kbench_conv133_sf_res4_speedup",
              "kbench_pw_x3d_res3_speedup", "kbench_platform",
              "kbench_dw_x3d_res3_speedup", "kbench_parity_ok",
              "kbench_error", "kbench_best",
              "serve_error", "serve_fill_ratio", "serve_p99_ms",
              "serve_p50_ms", "guard_rollbacks", "quarantined_clips",
              "train_recompiles", "obs_h2d_s",
              "mfu_peak_source", "mfu_source", "mfu_analytic",
              "obs_input_wait_frac",
              "obs_step_s", "trainer_error", "trainer_input_wait_frac",
              "trainer_mfu", "trainer_cps_chip",
              # the hbm triple sheds late (this arc's headline) and as a
              # unit-in-reverse: the source label must outlive the bytes
              # it qualifies, so the bytes drop first
              "hbm_attributed_frac", "hbm_peak_bytes", "hbm_source",
              "trainer_vs_rawstep", "detail", "step_ms_blocked",
              "tflops_per_sec"):  # drop one by one until it fits
        if len(json.dumps(out)) <= MAX_LINE_BYTES:
            break
        out.pop(k, None)
    if len(json.dumps(out)) > MAX_LINE_BYTES:
        out["models"] = {"dropped": "see bench_partial.json"}
    if len(json.dumps(out)) > MAX_LINE_BYTES:
        out["metric"] = out["metric"][:100]
        for k in ("error", "trainer_error"):
            if k in out:
                out[k] = out[k][:120]
    if len(json.dumps(out)) > MAX_LINE_BYTES:  # unconditional last resort
        out.pop("models", None)
    return out


if __name__ == "__main__":
    main()
