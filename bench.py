#!/usr/bin/env python
"""Benchmark harness: clips/sec/chip on the flagship training step.

Prints exactly ONE JSON line to stdout:
    {"metric": "...", "value": N, "unit": "clips/sec/chip", "vs_baseline": N}
(everything else goes to stderr). Run on the attached TPU by default; pass
--smoke for a CPU-sized sanity run.

Workload matches the reference launch recipe (run_slowfast_r50.sh:3-12,
SURVEY §6): SlowFast-R50, 32 frames, 256^2 crops, batch 8 per chip, bf16
compute (standing in for the recipe's fp16 AMP), measuring the compiled
train step (fwd+bwd+update, BN stats, metrics) end to end. `vs_baseline` is
reported as value / published-baseline when BASELINE.json carries a number;
the reference publishes none (SURVEY §6, "published": {}), so it defaults
to 1.0.
"""

import argparse
import json
import sys
import time


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="slowfast_r50")
    ap.add_argument("--batch_size", type=int, default=8)
    ap.add_argument("--num_frames", type=int, default=32)
    ap.add_argument("--crop", type=int, default=256)
    ap.add_argument("--alpha", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU-safe shapes for harness verification")
    args = ap.parse_args()

    if args.smoke:
        args.batch_size, args.num_frames, args.crop = 4, 8, 64
        args.steps, args.warmup = 3, 1

    import jax
    import jax.numpy as jnp
    import numpy as np

    if args.smoke:
        jax.config.update("jax_platforms", "cpu")

    from pytorchvideo_accelerate_tpu.config import MeshConfig, ModelConfig, OptimConfig
    from pytorchvideo_accelerate_tpu.models import create_model
    from pytorchvideo_accelerate_tpu.parallel.mesh import make_mesh
    from pytorchvideo_accelerate_tpu.parallel.sharding import shard_batch
    from pytorchvideo_accelerate_tpu.trainer import (
        TrainState, build_optimizer, make_train_step,
    )

    devices = jax.devices()
    n_chips = len(devices)
    log(f"devices: {n_chips} x {devices[0].device_kind} ({devices[0].platform})")

    mesh = make_mesh(MeshConfig(), devices=devices)
    num_classes = 700  # Kinetics-700 (BASELINE.json metric)
    model_cfg = ModelConfig(name=args.model, num_classes=num_classes,
                            slowfast_alpha=args.alpha)
    model = create_model(model_cfg, "bf16")

    B = args.batch_size * n_chips  # global batch: bench batch is per chip
    rng = np.random.default_rng(0)
    if args.model.startswith("slowfast"):
        batch = {
            "slow": rng.standard_normal(
                (B, args.num_frames // args.alpha, args.crop, args.crop, 3),
                dtype=np.float32),
            "fast": rng.standard_normal(
                (B, args.num_frames, args.crop, args.crop, 3), dtype=np.float32),
        }
        sample = (jnp.zeros((1, *batch["slow"].shape[1:])),
                  jnp.zeros((1, *batch["fast"].shape[1:])))
    else:
        batch = {"video": rng.standard_normal(
            (B, args.num_frames, args.crop, args.crop, 3), dtype=np.float32)}
        sample = jnp.zeros((1, *batch["video"].shape[1:]))
    batch["label"] = (rng.integers(0, num_classes, B)).astype(np.int32)

    log(f"global batch {B} ({args.batch_size}/chip), "
        f"{args.num_frames} frames @ {args.crop}^2")

    variables = model.init(jax.random.key(0), sample)
    tx = build_optimizer(OptimConfig(), total_steps=args.steps + args.warmup)
    state = TrainState.create(variables["params"], variables["batch_stats"], tx)
    step = make_train_step(model, tx, mesh)
    gb = shard_batch(mesh, batch)

    t0 = time.perf_counter()
    for i in range(args.warmup):
        state, metrics = step(state, gb, jax.random.key(i))
    jax.block_until_ready(metrics["loss"])
    log(f"warmup ({args.warmup} steps incl. compile): "
        f"{time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    for i in range(args.steps):
        state, metrics = step(state, gb, jax.random.key(100 + i))
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    clips_per_sec = B * args.steps / dt
    per_chip = clips_per_sec / n_chips
    log(f"{args.steps} steps in {dt:.2f}s -> {clips_per_sec:.2f} clips/s "
        f"({per_chip:.2f}/chip), step time {dt / args.steps * 1e3:.1f} ms, "
        f"final loss {float(metrics['loss']):.3f}")

    baseline = None
    try:
        published = json.load(open("BASELINE.json")).get("published", {})
        baseline = published.get("clips_per_sec_per_chip")
    except Exception:
        pass
    vs = per_chip / baseline if baseline else 1.0

    print(json.dumps({
        "metric": f"train clips/sec/chip ({args.model}, {args.num_frames}f, "
                  f"{args.crop}px, bf16{', smoke' if args.smoke else ''})",
        "value": round(per_chip, 3),
        "unit": "clips/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
